"""Autoscale decision-log report (ISSUE 12 tentpole, part 4).

Answers "why did the fleet grow at 14:03" from artifacts alone: every
`scale` trace event the autoscaler emitted (serve/autoscale.py) carries
the evidence that triggered it — burn rate, per-class SLO attainment,
measured queue wait, utilization, and the before/after fleet size —
and this tool renders the decision log with that per-decision evidence,
plus the run-level fleet economics (replica-seconds, time-weighted mean
fleet size, longest decision-free stretch).

Input: any JSONL carrying `trace` records — a `--metrics_log` from
`tools/serve_bench.py --trace --autoscale=...`, the `.events.jsonl`
written next to the Perfetto JSON, or a `flight-*.jsonl` dump. A
`run_end` record's counters (when present) supply the authoritative
`fleet_replica_seconds` / `scale_up` / `scale_down` totals; without
one, the decision events alone still tell the story.

Usage:
    python tools/fleet_report.py out/metrics.jsonl
    python tools/fleet_report.py serve_trace.events.jsonl --json
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.obs.report import load_records_with_skips  # noqa: E402
from avenir_tpu.obs.trace import record_event  # noqa: E402
from avenir_tpu.serve.autoscale import (  # noqa: E402
    mean_fleet_size,
    steady_window_s,
)

_EVIDENCE_KEYS = (
    "burn_rate", "attainment_interactive", "attainment_batch",
    "queue_wait_ms", "busy_frac", "queue_depth", "window_s", "replica",
    "spawn_s",
)


def load_fleet_records(path):
    records, skipped = load_records_with_skips(path)
    events = [record_event(r) for r in records
              if r.get("kind") == "trace" and "ev" in r]
    end = next((r for r in reversed(records)
                if r.get("kind") == "run_end"), None)
    return events, end, skipped


def summarize_fleet(events, run_end=None):
    """Decision log + run-level fleet facts as a plain dict;
    `format_fleet_report` renders it."""
    scales = sorted((e for e in events if e.get("ev") == "scale"),
                    key=lambda e: e["t"])
    anomalies = sorted((e for e in events if e.get("ev") == "anomaly"),
                       key=lambda e: e["t"])
    ts = [e["t"] for e in events]
    t0 = min(ts) if ts else 0.0
    t1 = max(ts) if ts else 0.0
    decisions = []
    for e in scales:
        # fleet health linkage (ISSUE 14): any anomaly inside this
        # decision's evidence window preceding it is the early-warning
        # context — "the health tier saw it coming at +12.3s"
        win = float(e.get("window_s") or 30.0)
        before = [
            {"t_rel_s": a["t"] - t0, "detector": a.get("detector"),
             "key": a.get("key")}
            for a in anomalies if e["t"] - win <= a["t"] <= e["t"]
        ]
        decisions.append({
            "t": e["t"],
            "t_rel_s": e["t"] - t0,
            "action": e.get("action"),
            "reason": e.get("reason"),
            "from_size": e.get("from_size"),
            "to_size": e.get("to_size"),
            "evidence": {k: e[k] for k in _EVIDENCE_KEYS if k in e},
            "anomalies_before": before,
        })
    by_action = {}
    for d in decisions:
        by_action[d["action"]] = by_action.get(d["action"], 0) + 1
    # weight lifecycle (ISSUE 20): the rollout campaign's decision
    # trail rides the same trace stream as `scale`, and renders the
    # same way — action, versions, replica, and the evidence attrs
    rollouts = []
    for e in sorted((e for e in events if e.get("ev") == "rollout"),
                    key=lambda e: e["t"]):
        rollouts.append({
            "t": e["t"],
            "t_rel_s": e["t"] - t0,
            "action": e.get("action"),
            "reason": e.get("reason"),
            "replica": e.get("replica"),
            "from_version": e.get("from_version"),
            "to_version": e.get("to_version"),
            "evidence": {k: e[k] for k in
                         ("mixing_s", "anomaly", "baseline_requests",
                          "canary_requests", "held_s", "swaps")
                         if k in e},
        })
    counters = (run_end or {}).get("counters") or {}
    initial = (decisions[0]["from_size"] if decisions else None)
    mean_size = None
    if decisions and t1 > t0:
        mean_size = mean_fleet_size(decisions, t0=t0, t1=t1,
                                    initial_size=initial)
    return {
        "n_decisions": len(decisions),
        "n_anomalies": len(anomalies),
        "by_action": by_action,
        "decisions": decisions,
        "rollouts": rollouts,
        "rollouts_started": counters.get("rollouts"),
        "rollbacks": counters.get("rollbacks"),
        "window_s": t1 - t0,
        "mean_fleet_size": mean_size,
        "steady_stretch_s": (steady_window_s(decisions, t0=t0, t1=t1)
                             if ts else 0.0),
        "replica_seconds": counters.get("fleet_replica_seconds"),
        "scale_up_counter": counters.get("scale_up"),
        "scale_down_counter": counters.get("scale_down"),
        "prewarm_ticks": counters.get("prewarm_ticks"),
    }


def _fmt_evidence(ev):
    bits = []
    if ev.get("burn_rate") is not None:
        bits.append(f"burn {ev['burn_rate']:.2f}")
    for cls in ("interactive", "batch"):
        a = ev.get(f"attainment_{cls}")
        if a is not None:
            bits.append(f"att[{cls}] {a:.0%}")
    if ev.get("queue_wait_ms") is not None:
        bits.append(f"queue_wait {ev['queue_wait_ms']:.0f}ms")
    if ev.get("busy_frac") is not None:
        bits.append(f"util {ev['busy_frac']:.0%}")
    if ev.get("queue_depth"):
        bits.append(f"qdepth {ev['queue_depth']}")
    if ev.get("window_s") is not None:
        bits.append(f"window {ev['window_s']:.0f}s")
    if ev.get("spawn_s") is not None:
        bits.append(f"spawn {ev['spawn_s'] * 1e3:.0f}ms")
    return "  ".join(bits)


def format_fleet_report(s):
    lines = ["== avenir fleet report (autoscale decision log) =="]
    head = [f"decisions: {s['n_decisions']}"]
    if s.get("n_anomalies"):
        head.append(f"anomalies: {s['n_anomalies']}")
    if s["by_action"]:
        head.append("(" + "  ".join(
            f"{k}={v}" for k, v in sorted(s["by_action"].items())) + ")")
    if s["window_s"]:
        head.append(f"over {s['window_s']:.1f}s traced")
    lines.append("  ".join(head))
    bill = []
    if s["replica_seconds"] is not None:
        bill.append(f"replica-seconds {s['replica_seconds']:.1f}")
    if s["mean_fleet_size"] is not None:
        bill.append(f"mean fleet {s['mean_fleet_size']:.2f}")
    if s["prewarm_ticks"]:
        bill.append(f"prewarm ticks {s['prewarm_ticks']:.0f}")
    if bill:
        lines.append("bill:      " + "   ".join(bill))
    if s["n_decisions"]:
        lines.append(f"steadiest: {s['steady_stretch_s']:.1f}s without "
                     "a decision (no-flapping check)")
        lines.append("")
        lines.append("-- decisions (each with the evidence that "
                     "triggered it) --")
        for d in s["decisions"]:
            lines.append(
                f"  t=+{d['t_rel_s']:8.2f}s  {d['action']:<12} "
                f"{d['from_size']} -> {d['to_size']}  "
                f"reason={d['reason']}")
            ev = _fmt_evidence(d["evidence"])
            if ev:
                lines.append(f"      {ev}")
            for a in d.get("anomalies_before") or []:
                lines.append(
                    f"      preceded by anomaly: {a['detector']} "
                    f"({a['key']}) at +{a['t_rel_s']:.2f}s")
    else:
        lines.append("no scale decisions in this log — a steady fleet "
                     "(or the autoscaler was not armed)")
    if s.get("rollouts"):
        lines.append("")
        lines.append("-- weight lifecycle (rollout decision log) --")
        for d in s["rollouts"]:
            who = (f" replica {d['replica']}"
                   if d.get("replica") is not None else "")
            lines.append(
                f"  t=+{d['t_rel_s']:8.2f}s  {d['action']:<14}"
                f"{who}  {d['from_version']} -> {d['to_version']}"
                + (f"  reason={d['reason']}" if d.get("reason") else ""))
            ev = d.get("evidence") or {}
            bits = []
            if ev.get("mixing_s") is not None:
                bits.append(f"mixing window {ev['mixing_s']:.2f}s")
            if ev.get("canary_requests"):
                bits.append(f"canary saw {ev['canary_requests']:.0f} "
                            "requests")
            if ev.get("baseline_requests"):
                bits.append(f"baseline {ev['baseline_requests']:.0f} "
                            "requests")
            if ev.get("swaps"):
                bits.append(f"{ev['swaps']:.0f} swaps")
            a = ev.get("anomaly")
            if isinstance(a, dict):
                bits.append(f"anomaly: {a.get('detector')} "
                            f"({a.get('key')}) value "
                            f"{a.get('value', float('nan')):.2f} vs "
                            f"threshold "
                            f"{a.get('threshold', float('nan')):.2f}")
            if bits:
                lines.append(f"      {'  '.join(bits)}")
        if s.get("rollbacks"):
            lines.append(f"  rollbacks this run: {s['rollbacks']:.0f} "
                         "(see rollback_begin rows above for the "
                         "trigger evidence)")
    return "\n".join(lines)


def main(argv):
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("--")]
    assert len(paths) == 1, (
        "usage: python tools/fleet_report.py <trace-events .jsonl> "
        "[--json]\n(a serve_bench --metrics_log, a *.events.jsonl, or "
        "a flight-*.jsonl dump)")
    events, run_end, _skipped = load_fleet_records(paths[0])
    if not events:
        print(f"no trace records in {paths[0]} — was the run traced? "
              "(tools/serve_bench.py --trace)", file=sys.stderr)
        return 1
    s = summarize_fleet(events, run_end)
    if as_json:
        print(json.dumps(s, indent=1))
    else:
        print(format_fleet_report(s))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
