"""VPU activation/RoPE microbench (VERDICT r2 item 3; the tool-shape that
found the erf-GELU +22% tax in round 2).

Measures, in ONE jit per variant (12x chained blocks so per-dispatch
overhead amortizes; a trailing 1-element D2H fetch is the only reliable
fence on the tunneled platform):

  act:  x -> fc(4d) -> ACT -> proj(d), 12 chained, fwd+bwd
        for ACT in {silu, tanh-gelu, erf-gelu, relu, identity}
        at the Llama-8B MLP shape (d=4096, ffn=14336, SwiGLU form for
        silu: gate*up like llama.py) and the GPT shape (768->3072).
  rope: Llama-8B attention projection chain (d=4096, 32:8 GQA heads,
        T=4096) with and without apply_rope on q/k — the delta is what
        RoPE actually costs inside a fused program.

Usage: python tools/bench_act.py [--exp=act|rope|all]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

L = 12


def timeit(fn, *args, warmup=2, iters=8):
    # fence = D2H of ONE element (sliced on device first — np.asarray on
    # the full leaf would drag the whole gradient through the tunnel);
    # block_until_ready alone returns early on this platform
    fence = lambda out: np.asarray(jax.tree.leaves(out)[0].ravel()[:1])
    for _ in range(warmup):
        out = fn(*args)
    fence(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    fence(out)
    return (time.perf_counter() - t0) / iters


ACTS = {
    "silu": jax.nn.silu,
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu_erf": lambda x: jax.nn.gelu(x, approximate=False),
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def bench_mlp_chain(B, T, d, ffn, swiglu_form):
    rng = np.random.default_rng(0)
    mk = lambda *s: jnp.asarray(
        rng.standard_normal(s).astype(np.float32) * 0.02, jnp.bfloat16)
    x = mk(B * T, d)
    if swiglu_form:
        params = [dict(wg=mk(d, ffn), wu=mk(d, ffn), wd=mk(ffn, d))
                  for _ in range(L)]
    else:
        params = [dict(wu=mk(d, ffn), wd=mk(ffn, d)) for _ in range(L)]
    for name, act in ACTS.items():
        if swiglu_form:
            def blockf(p, h, a=act):
                return h + (a(h @ p["wg"]) * (h @ p["wu"])) @ p["wd"]
            n_mm = 3
        else:
            def blockf(p, h, a=act):
                return h + a(h @ p["wu"]) @ p["wd"]
            n_mm = 2

        def loss(ps, h):
            for p in ps:
                h = blockf(p, h)
            return h.astype(jnp.float32).mean()

        g = jax.jit(jax.grad(loss, argnums=0))
        t = timeit(lambda: g(params, x))
        flops = 3 * 2 * B * T * d * ffn * n_mm * L  # fwd+2bwd passes
        print(f"  {name:10s} {t*1e3:8.2f} ms   {flops/t/1e12:6.1f} TF/s "
              f"({100*flops/t/197e12:4.1f}% of v5e peak)")


def bench_rope(B, T, d, n_head, n_kv_head):
    from avenir_tpu.models.common import head_major_merge, head_major_project
    from avenir_tpu.ops import apply_rope, rope_frequencies
    from avenir_tpu.ops.pallas.flash_attention import flash_attention

    hd = d // n_head
    rng = np.random.default_rng(0)
    mk = lambda *s: jnp.asarray(
        rng.standard_normal(s).astype(np.float32) * 0.02, jnp.bfloat16)
    x = mk(B, T, d)
    params = [dict(wq=mk(d, n_head * hd), wk=mk(d, n_kv_head * hd),
                   wv=mk(d, n_kv_head * hd), wo=mk(n_head * hd, d))
              for _ in range(L)]
    cos, sin = rope_frequencies(hd, T)

    def make_loss(use_rope):
        def block(p, h):
            q = head_major_project(h, p["wq"], None, n_head, hd)
            k = head_major_project(h, p["wk"], None, n_kv_head, hd)
            v = head_major_project(h, p["wv"], None, n_kv_head, hd)
            if use_rope:
                q = apply_rope(q, cos, sin, layout="bhtd")
                k = apply_rope(k, cos, sin, layout="bhtd")
            o = flash_attention(q, k, v, causal=True, layout="bhtd")
            return h + head_major_merge(o, p["wo"], None)

        def loss(ps, h):
            for p in ps:
                h = block(p, h)
            return h.astype(jnp.float32).mean()

        return jax.jit(jax.grad(loss, argnums=0))

    g0 = make_loss(False)
    g1 = make_loss(True)
    t0 = timeit(lambda: g0(params, x))
    t1 = timeit(lambda: g1(params, x))
    print(f"  attention chain without rope: {t0*1e3:8.2f} ms")
    print(f"  attention chain with rope:    {t1*1e3:8.2f} ms")
    print(f"  => rope tax over {L} layers (fwd+bwd, q+k): "
          f"{(t1-t0)*1e3:6.2f} ms ({100*(t1-t0)/t1:4.1f}% of the chain)")


def main():
    arg = sys.argv[1] if len(sys.argv) > 1 else "--exp=all"
    if "act" in arg or "all" in arg:
        print("GPT MLP shape (B=16 T=1024, 768->3072, act(fc(x))@proj):")
        bench_mlp_chain(16, 1024, 768, 3072, swiglu_form=False)
        print("Llama MLP shape (B=1 T=4096, 4096->14336, SwiGLU "
              "act(gate)*up form):")
        bench_mlp_chain(1, 4096, 4096, 14336, swiglu_form=True)
    if "rope" in arg or "all" in arg:
        print("Llama-8B attention shape (B=1 T=4096, 32:8 GQA, D=128):")
        bench_rope(1, 4096, 4096, 32, 8)


if __name__ == "__main__":
    main()
