"""Measure compiled temp-memory of ring attention fwd+bwd on the 8-CPU
harness (per-device, via XLA memory_analysis) — the A/B for the r5
blockwise rewrite (VERDICT r4 missing #6: the dense per-hop
(B,H,Tq,Tk) fp32 score matrices re-import the memory profile flash
attention exists to avoid).

Run: python tools/exp_ring_mem.py [T] [c] [B] [H] [H_kv] [D]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", "cpu")

from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.ring_attention import ring_causal_attention


def main():
    a = sys.argv[1:]
    T = int(a[0]) if len(a) > 0 else 4096
    c = int(a[1]) if len(a) > 1 else 2
    B = int(a[2]) if len(a) > 2 else 1
    H = int(a[3]) if len(a) > 3 else 8
    H_kv = int(a[4]) if len(a) > 4 else 2
    D = int(a[5]) if len(a) > 5 else 64
    mesh = make_mesh(f"context:{c}")
    jax.set_mesh(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(None, "context", None, None))
    rng = np.random.default_rng(0)
    q = jax.device_put(rng.standard_normal((B, T, H, D)).astype(np.float32), sh)
    k = jax.device_put(rng.standard_normal((B, T, H_kv, D)).astype(np.float32), sh)
    v = jax.device_put(rng.standard_normal((B, T, H_kv, D)).astype(np.float32), sh)

    def loss(q, k, v):
        return jnp.sum(ring_causal_attention(q, k, v) ** 2)

    comp = jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, k, v).compile()
    ma = comp.memory_analysis()
    print(f"T={T} c={c} B={B} H={H}/{H_kv} D={D}: "
          f"temp={ma.temp_size_in_bytes / 1e6:.1f} MB "
          f"(args {ma.argument_size_in_bytes / 1e6:.1f}, "
          f"out {ma.output_size_in_bytes / 1e6:.1f})")


if __name__ == "__main__":
    main()
