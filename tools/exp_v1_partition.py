"""Round-5 experiment: can the pallas flash kernel shard natively inside
partial-manual regions (VERDICT r4 item 1)?

Probes, each runnable standalone:
  A  check_vma=True shard_map over interpret-mode flash (plain mesh)
  B  nested shard_map (vma=True) inside a pipe-manual region
  C  nested shard_map (vma=False) inside a pipe-manual region (the r4 bug)
  D  custom_partitioning-wrapped reference attention inside the region
Run: python tools/exp_v1_partition.py A B C D

RESULTS (jax 0.9.0, shardy on, 2026-07-31 — what decided the r5 design):
  A/B FAIL — check_vma=True requires `vma` on the pallas out_shape, and
    even with it annotated the interpret-mode kernel body evaluates
    under the vma type system where kernel literals are vma-empty
    ("mul requires varying manual axes to match" in hlo_interpreter) —
    upstream; the static checker stays off for interpret pallas.
  C  PASSES in this toy (2e-6) — the toy is too symmetric; the real
    corruption needs per-stage-different weights (exp_v1_nested.py
    reproduces 2.8e-3 and pins the root cause: a nested shard_map
    with default axis_names claims replication over the enclosing
    Manual axis and its transpose psums cotangents across stages).
  D  FAIL — the custom_partitioning partition callback receives an
    EMPTY mesh inside a manual region ("Resource axis: data ... not
    found in mesh: ()"); custom_partitioning cannot partition ops in
    partial-manual regions on this jax.
Outcome: the product rule is axis_names=free_axis_names() on every
attention shard_map (partition.py), plus ring's position-as-data
workaround for nested axis_index (ring_attention.py).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from avenir_tpu.ops.pallas.flash_attention import flash_attention

B, T, H, D = 4, 64, 4, 16


def data(h_kv=None):
    h_kv = h_kv or H
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, h_kv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, h_kv, D), jnp.float32)
    return q, k, v


def oracle_loss(q, k, v):
    from avenir_tpu.ops.attention import causal_attention_reference

    return jnp.sum(causal_attention_reference(q, k, v) ** 2)


def flash_loss(q, k, v, wrap=None, check_vma=False):
    def att(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=True)

    if wrap is not None:
        att = jax.shard_map(att, in_specs=(wrap,) * 3, out_specs=wrap,
                            check_vma=check_vma)
    return jnp.sum(att(q, k, v) ** 2)


def probe_A():
    """check_vma=True shard_map over interpret flash on a plain mesh."""
    mesh = jax.make_mesh((2, 2), ("data", "tensor"))
    q, k, v = data()
    with jax.set_mesh(mesh):
        spec = P("data", None, "tensor", None)
        qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                      for x in (q, k, v))
        try:
            g = jax.jit(jax.grad(
                lambda q, k, v: flash_loss(q, k, v, wrap=spec,
                                           check_vma=True)))(qs, ks, vs)
            go = jax.jit(jax.grad(oracle_loss))(q, k, v)
            err = float(jnp.max(jnp.abs(g - go)))
            print(f"A: check_vma=True plain mesh OK, grad err {err:.2e}")
        except Exception as e:
            print(f"A: FAILED: {type(e).__name__}: {str(e)[:300]}")


def _pipe_region(att_in_region, q, k, v, mesh, vma_outer=False):
    """Minimal stand-in for the GPipe region: manual over 'pipe' only,
    activations replicated over pipe, per-stage weights sharded."""
    w = jnp.eye(D, dtype=jnp.float32)[None].repeat(2, 0)  # (stages, D, D)

    def body(w_local, q, k, v):
        h = jnp.einsum("bthd,de->bthe", q, w_local[0])
        o = att_in_region(h, k, v)
        o = jnp.einsum("bthd,de->bthe", o, w_local[0])
        return jax.lax.psum(o, "pipe") * 0.5  # fake 2-stage combine

    f = jax.shard_map(
        body,
        in_specs=(P("pipe"), P(None), P(None), P(None)),
        out_specs=P(None),
        check_vma=vma_outer, axis_names={"pipe"},
    )
    return jnp.sum(f(w, q, k, v) ** 2)


def probe_BC(vma_inner, tag):
    mesh = jax.make_mesh((2, 2), ("pipe", "data"))
    q, k, v = data()

    def att(h, k, v):
        spec = P("data", None, None, None)
        body = lambda ql, kl, vl: flash_attention(ql, kl, vl, causal=True,
                                                  interpret=True)
        return jax.shard_map(body, in_specs=(spec,) * 3, out_specs=spec,
                             check_vma=vma_inner)(h, k, v)

    def att_ref(h, k, v):  # oracle: xla attention, GSPMD handles it
        from avenir_tpu.ops.attention import causal_attention_reference

        return causal_attention_reference(h, k, v)

    with jax.set_mesh(mesh):
        try:
            g = jax.jit(jax.grad(
                lambda q, k, v: _pipe_region(att, q, k, v, mesh)))(q, k, v)
            go = jax.jit(jax.grad(
                lambda q, k, v: _pipe_region(att_ref, q, k, v, mesh)))(q, k, v)
            err = float(jnp.max(jnp.abs(g - go)))
            print(f"{tag}: nested vma={vma_inner} traced OK, grad err vs "
                  f"in-region-xla oracle: {err:.2e}")
        except Exception as e:
            print(f"{tag}: FAILED: {type(e).__name__}: {str(e)[:300]}")


def probe_D():
    """custom_partitioning inside the pipe-manual region (shardy on)."""
    from jax.experimental.custom_partitioning import custom_partitioning

    @custom_partitioning
    def att(q, k, v):
        from avenir_tpu.ops.attention import causal_attention_reference

        return causal_attention_reference(q, k, v)

    def infer(mesh, shapes, result_shape):
        return NamedSharding(mesh, P("data", None, None, None))

    def partition(mesh, shapes, result_shape):
        from avenir_tpu.ops.attention import causal_attention_reference

        arg_sh = (NamedSharding(mesh, P("data", None, None, None)),) * 3
        return mesh, causal_attention_reference, \
            NamedSharding(mesh, P("data", None, None, None)), arg_sh

    att.def_partition(
        infer_sharding_from_operands=infer, partition=partition,
        sharding_rule="b t h d, b t g d, b t g d -> b t h d",
    )
    mesh = jax.make_mesh((2, 2), ("pipe", "data"))
    q, k, v = data()
    with jax.set_mesh(mesh):
        try:
            val = jax.jit(lambda q, k, v: _pipe_region(
                lambda h, kk, vv: att(h, kk, vv), q, k, v, mesh))(q, k, v)
            ref = jax.jit(lambda q, k, v: _pipe_region(
                lambda h, kk, vv: oracle_att(h, kk, vv), q, k, v,
                mesh))(q, k, v)
            print(f"D: traced OK, val {float(val):.4f} vs ref "
                  f"{float(ref):.4f}")
        except Exception as e:
            print(f"D: FAILED: {type(e).__name__}: {str(e)[:300]}")


def oracle_att(h, k, v):
    from avenir_tpu.ops.attention import causal_attention_reference

    return causal_attention_reference(h, k, v)


if __name__ == "__main__":
    which = sys.argv[1:] or ["A", "B", "C", "D"]
    if "A" in which:
        probe_A()
    if "B" in which:
        probe_BC(True, "B")
    if "C" in which:
        probe_BC(False, "C")
    if "D" in which:
        probe_D()
