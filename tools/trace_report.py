"""TTFT attribution report from per-request trace events (ISSUE 10).

Answers the question the aggregate metrics can't: for the requests this
run served, where did time-to-first-token actually go — queue wait,
prefill work, the non-overlapped tail of a disaggregated page handoff
(transfer, ISSUE 13), or attempts lost to replica deaths (failover)?
The four components PARTITION each request's TTFT by construction
(obs/trace.request_segments), so the attribution sums to the measured
latency with no residue.

Input: any JSONL carrying `trace` records — a `--metrics_log` from
`tools/serve_bench.py --trace`, the `<trace>.events.jsonl` it writes
next to the Perfetto JSON, or an `out_dir/flight-*.jsonl` flight-
recorder dump. Other record kinds are ignored, so the same metrics.jsonl
feeds both this and tools/obs_report.py.

Usage:
    python tools/trace_report.py out/metrics.jsonl
    python tools/trace_report.py out/flight-replica0-death-001.jsonl
    python tools/trace_report.py serve_trace.events.jsonl --json
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.obs.report import percentile  # noqa: E402
from avenir_tpu.obs.trace import (  # noqa: E402
    record_event,
    request_segments,
    ttft_attribution,
)


def load_trace_events(path):
    """Trace events from a JSONL — the shared torn-line-tolerant reader
    (obs/report.py), filtered to `trace` records; skips are warned on
    stderr there, never silent."""
    from avenir_tpu.obs.report import load_records_with_skips

    records, _skipped = load_records_with_skips(path)
    return [record_event(r) for r in records
            if r.get("kind") == "trace" and "ev" in r]


def summarize_traces(events):
    """Per-request attribution + run-level percentiles. Returns a plain
    dict; format_trace_report renders it."""
    by_rid = {}
    for e in events:
        if e.get("rid") is not None:
            by_rid.setdefault(e["rid"], []).append(e)
    reqs = []
    for rid, evs in sorted(by_rid.items()):
        fin = next((e for e in evs if e["ev"] == "finish"), None)
        sub = next((e for e in evs if e["ev"] == "submit"), None)
        att = ttft_attribution(evs)
        reqs.append({
            "rid": rid,
            "priority": (sub or {}).get("priority"),
            "reason": (fin or {}).get("reason"),
            "failovers": sum(1 for e in evs if e["ev"] == "failover"),
            "chunks": sum(1 for e in evs if e["ev"] == "prefill_chunk"),
            "prefix_hit": any(e["ev"] == "prefix_hit" for e in evs),
            "cows": sum(1 for e in evs if e["ev"] == "cow"),
            # spec decoding samples the first token INSIDE admission
            # prefill (ISSUE 12 satellite): the event says so, and the
            # partition stays exact — prefill ends at the sample, not
            # at the verify tick that harvests it
            "admission_first": any(e["ev"] == "first_token"
                                   and e.get("admission")
                                   for e in evs),
            # disagg handoffs (ISSUE 13): how many times this request's
            # KV pages crossed the class boundary, and the bytes moved
            "handoffs": sum(1 for e in evs if e["ev"] == "kv_transfer"
                            and e.get("handoff")),
            "transfer_bytes": sum(int(e.get("bytes", 0)) for e in evs
                                  if e["ev"] == "kv_transfer"),
            "attribution": att,
            "segments": request_segments(evs),
        })
    with_ttft = [r for r in reqs if r["attribution"] is not None]
    ttfts = [r["attribution"]["ttft_s"] * 1e3 for r in with_ttft]

    def comp_ms(key):
        return [r["attribution"][key] * 1e3 for r in with_ttft]

    comps = {k: comp_ms(k + "_s")
             for k in ("queue", "prefill", "transfer", "failover")}
    total_ttft = sum(ttfts)
    return {
        "n_requests": len(reqs),
        "n_with_token": len(with_ttft),
        "n_failover": sum(1 for r in reqs if r["failovers"]),
        "n_handoff": sum(1 for r in reqs if r["handoffs"]),
        "transfer_bytes": sum(r["transfer_bytes"] for r in reqs),
        "n_admission_first": sum(1 for r in reqs if r["admission_first"]),
        "reasons": _count(r["reason"] for r in reqs),
        "ttft_p50_ms": percentile(ttfts, 0.50),
        "ttft_p99_ms": percentile(ttfts, 0.99),
        "ttft_total_ms": total_ttft,
        "components_ms": {k: sum(v) for k, v in comps.items()},
        "components_p99_ms": {k: percentile(v, 0.99)
                              for k, v in comps.items()},
        "requests": reqs,
    }


def _count(xs):
    out = {}
    for x in xs:
        out[x] = out.get(x, 0) + 1
    return out


def format_trace_report(s, *, detail_failovers=8):
    lines = ["== avenir trace report (TTFT attribution) =="]
    lines.append(
        f"requests traced: {s['n_requests']}  "
        f"(with >=1 token: {s['n_with_token']}, "
        f"survived a failover: {s['n_failover']})")
    if s.get("n_admission_first"):
        lines.append(
            f"spec decode: {s['n_admission_first']} first token(s) "
            "sampled inside admission prefill (TTFT anchors at the "
            "sample, not the verify tick that harvests it)")
    if s.get("n_handoff"):
        lines.append(
            f"disagg: {s['n_handoff']} request(s) handed prefill->"
            f"decode ({s['transfer_bytes'] / 1e6:.2f} MB of KV pages "
            "over frames; streamed ships hide behind prefill — only "
            "the `transfer` component below was user-visible)")
    if s["reasons"]:
        lines.append("finish reasons: " + "  ".join(
            f"{k}={v}" for k, v in sorted(s["reasons"].items(),
                                          key=lambda kv: str(kv[0]))))
    if s["ttft_p50_ms"] is not None:
        lines.append(f"ttft: p50 {s['ttft_p50_ms']:.1f} ms  "
                     f"p99 {s['ttft_p99_ms']:.1f} ms")
        lines.append("")
        lines.append("-- where TTFT went (sums over every first token; "
                     "the components partition each request's TTFT) --")
        total = s["ttft_total_ms"] or 1.0
        for k in ("queue", "prefill", "transfer", "failover"):
            ms = s["components_ms"][k]
            p99 = s["components_p99_ms"][k]
            lines.append(
                f"  {k:<9}{ms / 1e3:9.3f}s  {100.0 * ms / total:5.1f}%"
                + (f"   p99 {p99:8.1f} ms" if p99 is not None else ""))
        lines.append(f"  {'total':<9}{s['ttft_total_ms'] / 1e3:9.3f}s  "
                     "100.0%")
    fo = [r for r in s["requests"] if r["failovers"]
          and r["attribution"] is not None]
    if fo:
        fo.sort(key=lambda r: -r["attribution"]["ttft_s"])
        lines.append("")
        lines.append("-- failover survivors (worst TTFT first) --")
        for r in fo[:detail_failovers]:
            a = r["attribution"]
            lines.append(
                f"  rid {r['rid']:>4}  ttft {a['ttft_s'] * 1e3:8.1f} ms"
                f" = queue {a['queue_s'] * 1e3:7.1f}"
                f" + prefill {a['prefill_s'] * 1e3:7.1f}"
                f" + transfer {a.get('transfer_s', 0.0) * 1e3:6.1f}"
                f" + failover {a['failover_s'] * 1e3:7.1f} ms"
                f"  ({r['failovers']} failover(s), {r['chunks']} "
                f"chunk(s), finish={r['reason']})")
        if len(fo) > detail_failovers:
            lines.append(f"  ... and {len(fo) - detail_failovers} more")
    return "\n".join(lines)


def main(argv):
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("--")]
    assert len(paths) == 1, (
        "usage: python tools/trace_report.py <trace-events .jsonl> "
        "[--json]\n(a serve_bench --metrics_log, a *.events.jsonl, or "
        "a flight-*.jsonl dump)")
    events = load_trace_events(paths[0])
    if not events:
        print(f"no trace records in {paths[0]} — was the run traced? "
              "(tools/serve_bench.py --trace)", file=sys.stderr)
        return 1
    s = summarize_traces(events)
    if as_json:
        slim = {k: v for k, v in s.items() if k != "requests"}
        print(json.dumps(slim, indent=1))
    else:
        print(format_trace_report(s))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
