"""bf16 vs int8 quantized-training sweep (ISSUE 15 satellite).

One JSON artifact (committed as BENCH_r06.json) with a cell per
compute_dtype: ms/step, tokens/s/chip, peak HBM, and — the correctness
half — the loss@N trajectory parity between the cells from IDENTICAL
init and data. The parity gate is the acceptance: int8 must track the
bf16 curve within the documented tolerance band (docs/PERFORMANCE.md
"Past the bf16 plateau"; the same budget tests/test_quant.py pins in
tier-1), and both curves must actually learn.

Platform honesty (the BENCH_spec_decode caveat pattern): on this CPU
container the int8 cell measures the CORRECTNESS path — XLA:CPU
emulates the int8 dot, so ms/step is not the story and `ok` checks
parity, not speed. On a v5e the same tool measures the real step-time
win (int8 MXU peak ~2x bf16; run with --steps=40 on the bench chip and
refresh the ledger with tools/perf_gate.py --update).

Usage:
  python tools/quant_bench.py [--steps=128] [--seeds=1] [--out=FILE]
                              [--batch=N] [--block=N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.platform import honor_jax_platforms_env  # noqa: E402

# the documented parity tolerance budget (docs/PERFORMANCE.md; mirrored
# by tests/test_quant.py PARITY_MAX_ABS / PARITY_FINAL_ABS)
PARITY_MAX_ABS = 0.05
PARITY_FINAL_ABS = 0.02


def _learnable_tokens(steps, B, T, vocab, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    base = np.arange(steps * B * (T + 1)) % 7
    toks = (base * 9 + rng.integers(0, 2, base.shape)) % vocab
    toks = toks.reshape(steps, 1, B, T + 1)
    return toks[..., :-1].astype(np.int32), toks[..., 1:].astype(np.int32)


def run_cell(compute_dtype, *, dims, steps, seed, rounds=3):
    """One compute_dtype cell: trajectory (first dispatch, fixed data)
    plus median ms/step over `rounds` timed re-dispatches."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs.series import percentile
    from avenir_tpu.ops.quant import audit_quantization
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_multi_train_step, make_step_fns
    from avenir_tpu.utils.benching import peak_hbm_bytes

    cfg = GPTConfig(dropout=0.0, bias=True, compute_dtype=compute_dtype,
                    attn_impl=dims["attn_impl"], loss_impl="blocked",
                    block_size=dims["block"], vocab_size=dims["vocab"],
                    n_layer=dims["n_layer"], n_head=dims["n_head"],
                    n_embd=dims["n_embd"])
    m = GPT(cfg, rngs=nnx.Rngs(seed))
    graphdef, params = nnx.split(m, nnx.Param)
    # audit only the tensors the rules table quantizes (the counter's
    # documented meaning: dead channels WASTING int8 range)
    from avenir_tpu.parallel.partition import (
        match_precision_rules,
        rules_for_model,
    )

    flat = params.flat_state()
    pols = match_precision_rules(
        rules_for_model("gpt"), [p for p, _ in flat],
        {p: tuple(v.get_value().shape) for p, v in flat})
    clip = sum(audit_quantization(
        (("/".join(str(s) for s in p), np.asarray(v.get_value()))
         for p, v in flat if pols[p].quantize)).values())
    tx, _ = make_optimizer(params, learning_rate=3e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=10, lr_decay_iters=2 * steps,
                           min_lr=3e-4)
    opt = jax.jit(tx.init)(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_multi_train_step(step_fn, tx)
    xs, ys = _learnable_tokens(steps, dims["batch"], dims["block"],
                               dims["vocab"], seed)
    xs, ys = jnp.asarray(xs), jnp.asarray(ys)

    def host(t):
        return jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), t)

    # trajectory dispatch (includes compile; not timed)
    p, o, mtr = step(host(params), host(opt), jax.random.key(seed), xs, ys)
    losses = np.asarray(mtr["loss"]).astype(float)
    # timed rounds: fresh state copies per round (donated buffers)
    walls = []
    for _ in range(rounds):
        pr, orr = host(params), host(opt)
        t0 = time.perf_counter()
        pr, orr, mr = step(pr, orr, jax.random.key(seed), xs, ys)
        float(mr["loss"][-1])  # D2H fence
        walls.append(time.perf_counter() - t0)
    n_chips = jax.device_count()
    ms_step = percentile(walls, 0.5) / steps * 1e3
    tok_per_iter = dims["batch"] * dims["block"]
    return {
        "compute_dtype": compute_dtype,
        "ms_per_step": round(ms_step, 3),
        "tok_per_sec_per_chip": round(tok_per_iter / (ms_step / 1e3)
                                      / n_chips, 1),
        "peak_hbm_bytes": peak_hbm_bytes(),
        "loss_first": round(float(losses[0]), 6),
        "loss_last": round(float(losses[-1]), 6),
        "quant_scale_clip": clip,
        "losses": [round(float(v), 6) for v in losses],
    }


def main(argv):
    honor_jax_platforms_env()
    import numpy as np

    import jax

    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in argv}
    on_tpu = jax.default_backend() == "tpu"
    steps = int(args.get("steps", 128))
    seeds = int(args.get("seeds", 1))
    if on_tpu:
        dims = dict(n_layer=12, n_head=12, n_embd=768, vocab=50304,
                    block=int(args.get("block", 1024)),
                    batch=int(args.get("batch", 16)), attn_impl="pallas")
    else:
        dims = dict(n_layer=2, n_head=2, n_embd=32, vocab=64,
                    block=int(args.get("block", 16)),
                    batch=int(args.get("batch", 2)), attn_impl="xla")

    per_seed = []
    for s in range(seeds):
        cells = {cd: run_cell(cd, dims=dims, steps=steps, seed=s)
                 for cd in ("bfloat16", "int8")}
        lb = np.array(cells["bfloat16"].pop("losses"))
        li = np.array(cells["int8"].pop("losses"))
        d = np.abs(lb - li)
        per_seed.append({
            "seed": s, "cells": cells,
            "parity": {
                "max_abs_delta": round(float(d.max()), 6),
                "final_abs_delta": round(float(d[-1]), 6),
                "mean_abs_delta": round(float(d.mean()), 6),
            },
        })

    head = per_seed[0]
    parity = head["parity"]
    learned = all(
        r["cells"][cd]["loss_last"] < r["cells"][cd]["loss_first"] - 1.0
        for r in per_seed for cd in ("bfloat16", "int8"))
    ok = (learned
          and all(r["parity"]["max_abs_delta"] <= PARITY_MAX_ABS
                  and r["parity"]["final_abs_delta"] <= PARITY_FINAL_ABS
                  for r in per_seed))
    speed_ratio = (head["cells"]["bfloat16"]["ms_per_step"]
                   / head["cells"]["int8"]["ms_per_step"])
    out = {
        "kind": "quant_bench",
        "metric": "int8_vs_bf16_training",
        "cells": head["cells"],
        "parity": parity,
        "parity_budget": {"max_abs": PARITY_MAX_ABS,
                          "final_abs": PARITY_FINAL_ABS},
        "int8_step_speedup": round(speed_ratio, 4),
        "seeds": per_seed if seeds > 1 else None,
        "ok": bool(ok),
        "run_meta": {
            "device": str(jax.devices()[0].device_kind),
            "n_chips": jax.device_count(),
            "steps": steps, "dims": dims, "loss_impl": "blocked",
            "note": (
                "TPU cell: int8 MXU path, speedup is the headline"
                if on_tpu else
                "CPU container: the int8 cell exercises the blocked "
                "oracle numerics (XLA:CPU emulates the int8 dot, so "
                "ms/step is not the win here — parity is the gated "
                "claim; the ~2x step-time headline is the v5e int8-peak "
                "claim, docs/PERFORMANCE.md)"),
        },
    }
    js = json.dumps(out, indent=1)
    if "out" in args:
        with open(args["out"], "w") as f:
            f.write(js + "\n")
        print(f"wrote {args['out']} ok={ok}")
    else:
        print(js)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
