"""Round-3 layout experiments (dev tool, results land in BASELINE.md).

Measures, on the real chip, the cost of the (B,T,H,D)<->(B,H,T,D)
transposes around the flash kernels (the ~10.4ms xprof "data formatting"
bucket) and candidate ways to kill them:

  A. current: flash_attention() with wrapper transposes   [baseline]
  B. kernel on pre-transposed (B*H,T,D) data, no transposes in the
     timed region                                          [upper bound]
  C. per-head BlockSpec on the untransposed (B,T,H,D) array: grid
     (B,H,nq), block (1,block_q,1,D), head picked in the index_map so
     the "transpose" rides the HBM->VMEM DMA
  D. all-heads-per-grid-step on (B,T,H,D): grid (B,nq), block
     (1,block_q,H,D), static python loop over heads in-kernel

plus a block_q sweep for the fused fast-path backward (only the fwd
sweep was recorded in round 2).

Usage: python tools/exp_layout.py [--exp=abcd|sweep|block]
"""

import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from avenir_tpu.ops.pallas.flash_attention import (
    _compiler_params,
    _mask_scores,
    _branch,
    _make_bwd_fast,
    _make_fwd_fast,
    flash_attention,
)

B, T, H, D = 16, 1024, 12, 64
L = 12  # layers


def timeit(fn, *args, warmup=3, iters=10):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / iters


def make_data(layout):
    rng = np.random.default_rng(0)
    if layout == "bthd":
        shp = (B, T, H, D)
    else:
        shp = (B * H, T, D)
    mk = lambda: jnp.asarray(
        rng.standard_normal(shp).astype(np.float32) * 0.3, jnp.bfloat16)
    return mk(), mk(), mk()


# --------------------------------------------------------------------------
# C: per-head blocks via index_map on the untransposed (B, T, H, D) array
# --------------------------------------------------------------------------

def _fwd_kernel_c(q_ref, k_ref, v_ref, o_ref, *, block_q, causal, sm_scale,
                  seq_len):
    i = pl.program_id(2)
    nq = pl.num_programs(2)
    q = q_ref[0, :, 0, :]  # (BQ, D)
    tp = k_ref.shape[1]

    def _attend(kv_len):
        k = k_ref[0, :kv_len, 0, :]
        v = v_ref[0, :kv_len, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        s = _mask_scores(s, i * block_q, 0, causal, seq_len)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        o = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, :, 0, :] = (o / l).astype(o_ref.dtype)

    if causal and nq >= 2 and tp % 2 == 0:
        _branch((i + 1) * block_q <= tp // 2,
                lambda: _attend(tp // 2), lambda: _attend(tp))
    else:
        _attend(tp)


def fwd_c(q, k, v, block_q=512, sm_scale=None, causal=True):
    Bb, Tp, Hh, Dd = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(Dd)
    nq = Tp // block_q
    return pl.pallas_call(
        functools.partial(_fwd_kernel_c, block_q=block_q, causal=causal,
                          sm_scale=sm_scale, seq_len=Tp),
        grid=(Bb, Hh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, 1, Dd), lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((1, Tp, 1, Dd), lambda b, h, i: (b, 0, h, 0)),
            pl.BlockSpec((1, Tp, 1, Dd), lambda b, h, i: (b, 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, 1, Dd), lambda b, h, i: (b, i, h, 0)),
        out_shape=jax.ShapeDtypeStruct((Bb, Tp, Hh, Dd), q.dtype),
        compiler_params=_compiler_params(2),
    )(q, k, v)


# --------------------------------------------------------------------------
# D: all heads per grid step, static python loop over heads in-kernel
# --------------------------------------------------------------------------

def _fwd_kernel_d(q_ref, k_ref, v_ref, o_ref, *, block_q, causal, sm_scale,
                  seq_len, n_head):
    i = pl.program_id(1)
    nq = pl.num_programs(1)
    tp = k_ref.shape[1]

    def _attend(kv_len):
        for h in range(n_head):
            q = q_ref[0, :, h, :]
            k = k_ref[0, :kv_len, h, :]
            v = v_ref[0, :kv_len, h, :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale
            s = _mask_scores(s, i * block_q, 0, causal, seq_len)
            m = jnp.max(s, axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
            o = jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            o_ref[0, :, h, :] = (o / l).astype(o_ref.dtype)

    if causal and nq >= 2 and tp % 2 == 0:
        _branch((i + 1) * block_q <= tp // 2,
                lambda: _attend(tp // 2), lambda: _attend(tp))
    else:
        _attend(tp)


def fwd_d(q, k, v, block_q=512, sm_scale=None, causal=True):
    Bb, Tp, Hh, Dd = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(Dd)
    nq = Tp // block_q
    return pl.pallas_call(
        functools.partial(_fwd_kernel_d, block_q=block_q, causal=causal,
                          sm_scale=sm_scale, seq_len=Tp, n_head=Hh),
        grid=(Bb, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, Hh, Dd), lambda b, i: (b, i, 0, 0)),
            pl.BlockSpec((1, Tp, Hh, Dd), lambda b, i: (b, 0, 0, 0)),
            pl.BlockSpec((1, Tp, Hh, Dd), lambda b, i: (b, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, Hh, Dd), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((Bb, Tp, Hh, Dd), q.dtype),
        compiler_params=_compiler_params(1),
    )(q, k, v)


def run_abcd():
    sm = 1.0 / math.sqrt(D)
    # A: current public entry (transposes inside), fwd+bwd through vjp
    q, k, v = make_data("bthd")

    def loss_a(q_, k_, v_):
        out = flash_attention(q_, k_, v_, causal=True)
        return out.astype(jnp.float32).mean()

    g_a = jax.jit(jax.grad(loss_a, argnums=(0, 1, 2)))
    ta = timeit(lambda: g_a(q, k, v))
    print(f"A  flash_attention (w/ transposes)  fwd+bwd x1: {ta*1e3:7.2f} ms"
          f"  x{L}: {ta*L*1e3:7.2f} ms")

    # B: kernel math only on pre-transposed data
    qt, kt, vt = make_data("bhtd")
    fwd_impl = _make_fwd_fast(T, H, H)
    bwd_impl = _make_bwd_fast(T, H, H)

    @jax.custom_vjp
    def f(q_, k_, v_):
        return fwd_impl(q_, k_, v_, True, sm, 512, False)

    def f_fwd(q_, k_, v_):
        o = fwd_impl(q_, k_, v_, True, sm, 512, False)
        return o, (q_, k_, v_, o)

    def f_bwd(res, do):
        q_, k_, v_, o = res
        return bwd_impl(q_, k_, v_, o, do, True, sm, 512, 1024, False)

    f.defvjp(f_fwd, f_bwd)

    def loss_b(q_, k_, v_):
        return f(q_, k_, v_).astype(jnp.float32).mean()

    g_b = jax.jit(jax.grad(loss_b, argnums=(0, 1, 2)))
    tb = timeit(lambda: g_b(qt, kt, vt))
    print(f"B  kernel only (no transposes)      fwd+bwd x1: {tb*1e3:7.2f} ms"
          f"  x{L}: {tb*L*1e3:7.2f} ms")
    print(f"   => transpose tax per layer: {(ta-tb)*1e3:6.2f} ms"
          f"  x{L}: {(ta-tb)*L*1e3:6.2f} ms")

    # C: per-head index_map DMA (fwd only first — feasibility + speed)
    try:
        jc = jax.jit(fwd_c)
        # correctness vs A's forward
        oc = jc(q, k, v)
        oa = flash_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(oc.astype(jnp.float32)
                                    - oa.astype(jnp.float32))))
        tc = timeit(lambda: jc(q, k, v))
        print(f"C  per-head index_map DMA            fwd x1: {tc*1e3:7.2f} ms"
              f"  max|err|={err:.2e}")
    except Exception as e:  # noqa: BLE001
        print(f"C  per-head index_map DMA: FAILED: {type(e).__name__}: "
              f"{str(e)[:300]}")

    # D: all heads per grid step
    try:
        jd = jax.jit(fwd_d)
        od = jd(q, k, v)
        oa = flash_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(od.astype(jnp.float32)
                                    - oa.astype(jnp.float32))))
        td = timeit(lambda: jd(q, k, v))
        print(f"D  all-heads static loop             fwd x1: {td*1e3:7.2f} ms"
              f"  max|err|={err:.2e}")
    except Exception as e:  # noqa: BLE001
        print(f"D  all-heads static loop: FAILED: {type(e).__name__}: "
              f"{str(e)[:300]}")

    # fwd-only baselines for C/D comparison
    qt, kt, vt = make_data("bhtd")
    jfwd = jax.jit(lambda q_, k_, v_: fwd_impl(q_, k_, v_, True, sm, 512,
                                               False))
    tf = timeit(lambda: jfwd(qt, kt, vt))
    print(f"B' kernel-only                       fwd x1: {tf*1e3:7.2f} ms")

    def fwd_with_t(q_, k_, v_):
        qt_ = q_.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        kt_ = k_.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        vt_ = v_.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        o = fwd_impl(qt_, kt_, vt_, True, sm, 512, False)
        return o.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    jfa = jax.jit(fwd_with_t)
    tfa = timeit(lambda: jfa(q, k, v))
    print(f"A' transposes + kernel               fwd x1: {tfa*1e3:7.2f} ms")


def run_sweep():
    """block_q sweep for the fused fast-path backward (bwd alone)."""
    sm = 1.0 / math.sqrt(D)
    qt, kt, vt = make_data("bhtd")
    fwd_impl = _make_fwd_fast(T, H, H)
    o = jax.jit(lambda a, b_, c: fwd_impl(a, b_, c, True, sm, 512, False))(
        qt, kt, vt)
    do = jnp.ones_like(o)
    bwd_impl = _make_bwd_fast(T, H, H)
    for bq in (128, 256, 512, 1024):
        try:
            jb = jax.jit(lambda a, b_, c, o_, d_: bwd_impl(
                a, b_, c, o_, d_, True, sm, bq, 1024, False))
            t = timeit(lambda: jb(qt, kt, vt, o, do))
            print(f"fused bwd block_q={bq:5d}: {t*1e3:7.2f} ms"
                  f"  x{L}: {t*L*1e3:7.2f} ms")
        except Exception as e:  # noqa: BLE001
            print(f"fused bwd block_q={bq:5d}: FAILED {type(e).__name__}: "
                  f"{str(e)[:200]}")
    # fwd sweep re-check at current default
    for bq in (256, 512, 1024):
        jf = jax.jit(lambda a, b_, c: fwd_impl(a, b_, c, True, sm, bq, False))
        t = timeit(lambda: jf(qt, kt, vt))
        print(f"fast fwd  block_q={bq:5d}: {t*1e3:7.2f} ms")


if __name__ == "__main__":
    arg = sys.argv[1] if len(sys.argv) > 1 else "--exp=abcd"
    if "sweep" in arg:
        run_sweep()
    else:
        run_abcd()
