"""CLI wrapper: metrics.jsonl -> human-readable goodput/timing summary.

Usage:
    python tools/obs_report.py out/metrics.jsonl

All logic lives in avenir_tpu/obs/report.py (importable for tests and
notebooks); this file only handles being run from the repo root or from
tools/.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.obs.report import main  # noqa: E402

if __name__ == "__main__":
    main(sys.argv[1:])
