"""Seeded-Poisson load generator for the serve fleet (ISSUE 2 + 6 + 8).

Drives `avenir_tpu/serve.Router` (N replicas over one model;
`--n_replicas=1` is the single-engine case) with exponential
interarrivals on the wall clock and reports TTFT / TPOT p50/p99,
goodput, and per-priority-class SLO attainment — the fraction of
requests meeting a TTFT/TPOT target (ISSUE 6 satellite). The request
mix (prompt lengths, budgets, priorities, arrival times) is fully
determined by --seed; by default the model is a tiny random-init GPT so
the bench runs anywhere (pass --out_dir to serve a trained ckpt.pt).

`--kv_impl=paged` (ISSUE 9) serves from the paged KV pool
(`--page_size/--n_pages/--max_pages_per_seq/--prefill_chunk/`
`--prefix_sharing`); `--sweep` ignores the load-generator flags and
instead binary-searches offered CLOSED-LOOP concurrency for the **max
sustainable concurrency** at the `--slo_ttft_ms/--slo_tpot_ms` targets
(`--min_attainment` of requests must meet both), running slab vs paged
at EQUAL KV HBM (`--kv_budget_tokens`) on a long-prompt/short-output
mix with a shared system prefix (`--shared_prefix`), and emits a
BENCH JSON (`--out`, default BENCH_paged_kv.json) whose headline is
the paged/slab concurrency ratio.

`--backend=process` (ISSUE 8) runs each replica as its own worker
process; `--kills=K` delivers K replica kills at evenly spaced
completion milestones (REAL SIGKILLs to worker processes under the
process backend, `kill_replica` under inproc) and reports **failover
MTTR**: kill -> the first re-dispatched request's first token on a
surviving replica (estimated from per-request TTFT, which counts from
ORIGINAL submission and — because failover discards the dead attempt's
tokens — ends at the re-dispatched first token). Process-backend kills
recover via the respawn supervisor; inproc kills are revived a fixed
number of steps later.

    python tools/serve_bench.py --n_requests=64 --rate=20 --n_slots=4 \
        --n_replicas=2 --batch_frac=0.5 --slo_ttft_ms=500 \
        --max_new_tokens=32 --metrics_log=/tmp/serve/metrics.jsonl
    python tools/serve_bench.py --backend=process --n_replicas=2 \
        --kills=1 --n_requests=48 --rate=30

--metrics_log writes an obs JSONL (run_meta / request / run_end) that
`python tools/obs_report.py <log>` summarizes.

`--trace[=path.json]` (ISSUE 10) arms per-request causal tracing: the
run writes a Perfetto-loadable Chrome trace JSON (request waterfalls —
queue/prefill/failover/decode — next to the serve phase spans), a
sibling `.events.jsonl`, `trace` records into --metrics_log, and
flight-recorder dumps (`flight-*.jsonl`) on every replica death.
`python tools/trace_report.py <events/log>` attributes TTFT across
queue vs prefill vs failover per request.

`--anomaly` (ISSUE 14) arms the fleet health engine
(avenir_tpu/obs/anomaly.py): the router feeds step-time / heartbeat /
queue-wait / TTFT / TPOT series each step and the detector table fires
`anomaly` records + trace events + flight dumps on drift, trend or
collapse — BEFORE the stall/SLO tiers react. With or without the flag,
TTFT/TPOT percentiles are reported from the shared streaming sketch
(obs/series.QuantileSketch) and the run_end record carries the sketch
snapshots so obs_report prints p50/p99 without re-deriving them.

`--load_shape={poisson,bursty,diurnal}` (ISSUE 12) swaps the arrival
process: seeded non-homogeneous generators (thinning) whose config
rides run_meta, so any shape replays bit-identically.
`--autoscale=<max_replicas>` arms the elastic control plane
(serve/autoscale.py): the fleet follows SLO burn rate + measured
queue wait between --min_replicas and max, every decision a traced
`scale` event (`python tools/fleet_report.py <log>` prints the
decision log). `--autoscale_bench` runs the ISSUE 12 acceptance
sweep — autoscale vs every static fleet size on the seeded diurnal
shape — and writes BENCH_autoscale.json.

`--kv_cdn` (ISSUE 17) runs the fleet KV-reuse acceptance sweep: N
tenants with per-tenant system prompts arriving on merged seeded
Poisson schedules, `Router(affinity=...)` on vs off at equal chips,
under a page pool deliberately too small for every tenant to stay
cached everywhere. Writes BENCH_kv_cdn.json (max sustainable
concurrency frontier + open-loop TTFT p99 probe + the reuse-audit
missed_reuse_frac the PERF ledger bands).
"""

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

import numpy as np  # noqa: E402

from avenir_tpu.obs.report import percentile  # noqa: E402


def gen_arrivals(shape, rng, n, rate, *, burst_mult=6.0, quiet_frac=0.25,
                 burst_period_s=6.0, burst_duty=0.25, period_s=20.0,
                 amp=0.8):
    """Seeded arrival-time generators (ISSUE 12 satellite). Returns
    (arrival times, config dict) — the config rides run_meta and the
    BENCH json so any run replays bit-identically from (seed, params).

      poisson   homogeneous exponential interarrivals (the PR 2 shape)
      bursty    Poisson bursts over a quiet floor: rate x quiet_frac
                outside bursts, rate x burst_mult inside; bursts occupy
                the first burst_duty of every burst_period_s window
      diurnal   sinusoidal rate: rate x (1 + amp sin(2 pi t/period_s))
                — the day/night swing, compressed to bench scale

    Non-homogeneous shapes draw by Lewis-Shedler thinning: candidates
    at the peak rate, each kept with probability lambda(t)/lambda_max
    from the SAME seeded stream — a pure function of (seed, params)."""
    cfg = {"load_shape": shape, "rate": rate}
    if shape == "poisson":
        return np.cumsum(rng.exponential(1.0 / rate, n)), cfg
    if shape == "bursty":
        cfg.update(burst_mult=burst_mult, quiet_frac=quiet_frac,
                   burst_period_s=burst_period_s, burst_duty=burst_duty)
        lam_max = rate * burst_mult

        def lam(t):
            in_burst = (t % burst_period_s) < burst_duty * burst_period_s
            return rate * (burst_mult if in_burst else quiet_frac)
    elif shape == "diurnal":
        cfg.update(period_s=period_s, amp=amp)
        assert 0.0 <= amp < 1.0, "amp must be in [0, 1) — the rate " \
            "must stay positive for thinning"
        lam_max = rate * (1.0 + amp)

        def lam(t):
            return rate * (1.0 + amp * math.sin(2.0 * math.pi * t
                                                / period_s))
    else:
        raise ValueError(f"unknown load_shape {shape!r} "
                         "(poisson | bursty | diurnal)")
    out, t = [], 0.0
    while len(out) < n:
        t += rng.exponential(1.0 / lam_max)
        if rng.random() * lam_max <= lam(t):
            out.append(t)
    return np.asarray(out), cfg


def _load_cfg_from_args(args):
    shape = args.get("load_shape", "poisson")
    kw = {}
    for flag, cast in (("burst_mult", float), ("quiet_frac", float),
                       ("burst_period_s", float), ("burst_duty", float),
                       ("period_s", float), ("amp", float)):
        if flag in args:
            kw[flag] = cast(args[flag])
    return shape, kw


def _pct(xs, q):
    """percentile, rendered as nan on an empty list for the f-strings."""
    p = percentile(xs, q)
    return float("nan") if p is None else p


def slo_attainment(finished, *, slo_ttft_ms, slo_tpot_ms):
    """Fraction of requests meeting the SLO — served (tokens
    delivered, not shed/timed out) within both targets, tpot binding
    only where defined. Delegates per-request scoring to the ONE
    shared rule (`serve/autoscale.request_met_slo`) so the number the
    bench scores IS the number the autoscaler steers on; door
    rejections (impossible shapes — user error, not capacity) are
    excluded from the denominator, same as the SLOEngine window."""
    from avenir_tpu.serve.autoscale import request_met_slo

    scored = [f for f in finished if f.finish_reason != "rejected"]
    if not scored:
        return None
    return sum(request_met_slo(f, slo_ttft_ms=slo_ttft_ms,
                               slo_tpot_ms=slo_tpot_ms)
               for f in scored) / len(scored)


def _kv_engine_kwargs(args):
    """Paged-KV / kv_dtype / spec engine knobs from flags (None entries
    use Engine defaults). These ride the process backend's hello
    unchanged (serve/proc.py)."""
    kv_impl = args.get("kv_impl", "slab")
    assert kv_impl in ("slab", "paged"), kv_impl
    kw = {}
    if kv_impl == "paged":
        kw["kv_impl"] = "paged"
        for flag, cast in (("page_size", int), ("n_pages", int),
                           ("max_pages_per_seq", int),
                           ("prefill_chunk", int)):
            if flag in args:
                kw[flag] = cast(args[flag])
        if "prefix_sharing" in args:
            kw["prefix_sharing"] = args["prefix_sharing"] not in ("0",
                                                                  "false")
    if args.get("kv_dtype"):
        kw["kv_dtype"] = args["kv_dtype"]
    if args.get("spec_k"):
        kw["spec_decode"] = "draft"
        kw["spec_k"] = int(args["spec_k"])
    return kw or None


def _closed_loop_trial(engine, prompts, *, n_conc, n_requests, max_new,
                       top_k):
    """Closed-loop load: keep `n_conc` requests in flight until
    `n_requests` finish. A full pass over the distinct prompt set runs
    (and is discarded) first, so every prefill/chunk bucket is compiled
    — and the prefix cache warmed — before the measured window.
    Returns the measured FinishedRequests."""
    import itertools

    for p in prompts:  # warmup: all buckets compile, prefix cache fills
        engine.submit(list(p), max_new_tokens=max_new, temperature=1.0,
                      top_k=top_k)
    engine.drain()
    prompt_iter = itertools.cycle(prompts)
    submitted = 0
    done = []
    while len(done) < n_requests:
        while submitted < n_requests and (submitted - len(done)) < n_conc:
            engine.submit(list(next(prompt_iter)), max_new_tokens=max_new,
                          temperature=1.0, top_k=top_k)
            submitted += 1
        done.extend(engine.step())
    engine.drain()
    return done


def sweep(args):
    """Binary-search max sustainable closed-loop concurrency at the
    TTFT/TPOT SLO, slab vs paged at EQUAL KV HBM, on a long-prompt/
    short-output mix sharing one system prefix — the ISSUE 9 headline.

    `--kv_dtype_axis` (ISSUE 11) extends the sweep to a kv_dtype axis:
    each (slab|paged) x (bf16|int8) cell runs at EQUAL KV HBM — int8
    cells get 2x the TOKEN budget, because that is what equal bytes
    buys them (per-head fp32 scales add ~6% which the budget ignores;
    recorded in the config) — and the artifact (default
    BENCH_spec_decode.json) carries the TTFT/TPOT p50/p99 +
    max-sustainable-concurrency frontier per cell plus the int8/bf16
    concurrency ratios.
    """
    import json

    from flax import nnx

    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Engine
    from avenir_tpu.models.gpt import GPT, GPTConfig

    # the defaults make service time DOMINATE the SLO on CPU (a 4-layer
    # model, 288-352 token prompts, 16 output tokens): a closed-loop
    # request that must WAIT for capacity visibly blows the TTFT
    # target, so "sustainable" measures real residency, not how much
    # queueing hides inside a generous SLO
    seed = int(args.get("seed", 0))
    block_size = int(args.get("block_size", 512))
    kv_budget = int(args.get("kv_budget_tokens", 2048))
    page_size = int(args.get("page_size", 16))
    shared_prefix = int(args.get("shared_prefix", 256))
    tail_min = int(args.get("tail_min", 32))
    tail_max = int(args.get("tail_max", 96))
    max_new = int(args.get("max_new_tokens", 16))
    n_requests = int(args.get("sweep_requests", 48))
    max_conc = int(args.get("max_concurrency", 32))
    slo_ttft_ms = float(args.get("slo_ttft_ms", 250.0))
    slo_tpot_ms = float(args.get("slo_tpot_ms", 50.0))
    min_att = float(args.get("min_attainment", 0.9))
    dtype_axis = "kv_dtype_axis" in args
    out_path = args.get("out", "BENCH_spec_decode.json" if dtype_axis
                        else "BENCH_paged_kv.json")
    assert shared_prefix + tail_max + max_new <= block_size

    model = GPT(GPTConfig(
        block_size=block_size, vocab_size=int(args.get("vocab_size", 256)),
        n_layer=int(args.get("n_layer", 4)),
        n_head=int(args.get("n_head", 2)),
        n_embd=int(args.get("n_embd", 128)),
        dropout=0.0, bias=True, attn_impl="xla"), rngs=nnx.Rngs(seed))
    cfg = model.config

    mix_rng = np.random.default_rng(seed)
    prefix = [int(t) for t in mix_rng.integers(0, cfg.vocab_size,
                                               shared_prefix)]
    prompts = [
        prefix + [int(t) for t in mix_rng.integers(
            0, cfg.vocab_size, int(mix_rng.integers(tail_min,
                                                    tail_max + 1)))]
        for _ in range(24)
    ]

    def build(impl, kv_dtype="bf16"):
        # EQUAL KV HBM: the slab spends kv_budget tokens on n_slots
        # full-width columns; the paged pool spends the same tokens on
        # pages (slots are cheap decode state, so paged raises n_slots
        # to whatever the sweep might sustain — that decoupling IS the
        # subsystem's point). int8 halves bytes/token, so equal HBM
        # means DOUBLE the token budget (the ISSUE 11 axis).
        budget = kv_budget * (2 if kv_dtype == "int8" else 1)
        if impl == "slab":
            n_slots = max(1, budget // block_size)
            return Engine(model, n_slots=n_slots, kv_dtype=kv_dtype,
                          registry=MetricsRegistry()), n_slots
        n_pages = budget // page_size
        eng = Engine(model, n_slots=max_conc, registry=MetricsRegistry(),
                     kv_impl="paged", page_size=page_size,
                     n_pages=n_pages, kv_dtype=kv_dtype)
        return eng, n_pages

    def sustainable(impl, n_conc, kv_dtype="bf16"):
        eng, _ = build(impl, kv_dtype)
        done = _closed_loop_trial(
            eng, prompts, n_conc=n_conc, n_requests=n_requests,
            max_new=max_new, top_k=None)
        att = slo_attainment(done, slo_ttft_ms=slo_ttft_ms,
                             slo_tpot_ms=slo_tpot_ms)
        ttfts = [f.ttft_ms for f in done if f.ttft_ms is not None]
        tpots = [f.tpot_ms for f in done if f.n_out > 1]
        stats = {"n_conc": n_conc, "attainment": att,
                 "ttft_p50_ms": _pct(ttfts, 0.50),
                 "ttft_p99_ms": _pct(ttfts, 0.99),
                 "tpot_p50_ms": _pct(tpots, 0.50),
                 "tpot_p99_ms": _pct(tpots, 0.99)}
        if impl == "paged":
            a = eng._paged.alloc.stats()
            stats["prefix_hit_rate"] = eng._paged.prefix_hit_rate()
            stats["cow_copies"] = a["cow_copies"]
        print(f"[sweep:{impl}:{kv_dtype}] n={n_conc:3d}  "
              f"attainment {att:6.1%}  "
              f"ttft p99 {stats['ttft_p99_ms']:7.1f} ms  "
              f"tpot p99 {stats['tpot_p99_ms']:6.2f} ms")
        return att is not None and att >= min_att, stats

    def frontier(impl, kv_dtype="bf16"):
        trials = []
        ok1, st = sustainable(impl, 1, kv_dtype)
        trials.append(st)
        if not ok1:
            return {"max_sustainable_concurrency": 0, "trials": trials}
        lo, hi = 1, max_conc
        while lo < hi:
            mid = (lo + hi + 1) // 2
            ok, st = sustainable(impl, mid, kv_dtype)
            trials.append(st)
            if ok:
                lo = mid
            else:
                hi = mid - 1
        return {"max_sustainable_concurrency": lo, "trials": trials}

    if dtype_axis:
        results = {}
        for impl in ("slab", "paged"):
            for kv_dtype in ("bf16", "int8"):
                results[f"{impl}_{kv_dtype}"] = frontier(impl, kv_dtype)
        maxes = {k: v["max_sustainable_concurrency"]
                 for k, v in results.items()}
        ratios = {
            impl: (maxes[f"{impl}_int8"] / maxes[f"{impl}_bf16"]
                   if maxes[f"{impl}_bf16"] else float("inf"))
            for impl in ("slab", "paged")
        }
        bench = {
            "kind": "kv_dtype_sweep",
            "config": {
                "seed": seed, "block_size": block_size,
                "kv_budget_tokens": kv_budget,
                "int8_token_budget": kv_budget * 2,
                "int8_scale_overhead_note":
                    "per-(position, head) fp32 scales add ~4/head_dim "
                    "bytes/token, excluded from the equal-HBM budget",
                "page_size": page_size, "shared_prefix": shared_prefix,
                "tail_tokens": [tail_min, tail_max],
                "max_new_tokens": max_new, "n_requests": n_requests,
                "slo_ttft_ms": slo_ttft_ms, "slo_tpot_ms": slo_tpot_ms,
                "min_attainment": min_att,
            },
            **results,
            "max_sustainable_concurrency": maxes,
            "int8_vs_bf16_concurrency_ratio": ratios,
            # the acceptance bar (ISSUE 11): int8 at equal HBM must buy
            # >= 1.8x sustainable concurrency where CAPACITY is the
            # hard bound — the slab axis (capacity == n_slots exactly,
            # so the ratio measures pure bytes-per-token). The paged
            # cells run the CPU REFERENCE dequant (gather + multiply
            # per tick), whose extra host compute eats into the
            # capacity win at high concurrency; the TPU path is the
            # fused int8 kernel where the dequant rides the halved DMA
            # (ops/pallas/paged_attention.paged_attention_int8), which
            # this CPU sweep cannot time — both ratios are recorded.
            "ok": ratios["slab"] >= 1.8,
            "note": ("slab ratio is the capacity acceptance (hard "
                     "n_slots bound); paged cells pay the reference-"
                     "path dequant on CPU — on TPU the fused int8 "
                     "kernel halves the page DMA instead"),
        }
        with open(out_path, "w") as f:
            json.dump(bench, f, indent=1)
        print(f"[sweep] max sustainable concurrency at SLO: "
              + "  ".join(f"{k}={v}" for k, v in maxes.items()))
        print(f"[sweep] int8/bf16 ratio: slab {ratios['slab']:.2f}x  "
              f"paged {ratios['paged']:.2f}x  -> {out_path}")
        return 0 if bench["ok"] else 1

    results = {impl: frontier(impl) for impl in ("slab", "paged")}
    slab_max = results["slab"]["max_sustainable_concurrency"]
    paged_max = results["paged"]["max_sustainable_concurrency"]
    ratio = paged_max / slab_max if slab_max else float("inf")
    bench = {
        "kind": "paged_kv_sweep",
        "config": {
            "seed": seed, "block_size": block_size,
            "kv_budget_tokens": kv_budget, "page_size": page_size,
            "shared_prefix": shared_prefix,
            "tail_tokens": [tail_min, tail_max],
            "max_new_tokens": max_new, "n_requests": n_requests,
            "slo_ttft_ms": slo_ttft_ms, "slo_tpot_ms": slo_tpot_ms,
            "min_attainment": min_att,
        },
        "slab": results["slab"],
        "paged": results["paged"],
        "concurrency_ratio": ratio,
        "ok": slab_max > 0 and ratio >= 2.0,
    }
    with open(out_path, "w") as f:
        json.dump(bench, f, indent=1)
    print(f"[sweep] max sustainable concurrency at SLO "
          f"(ttft<={slo_ttft_ms:.0f}ms, tpot<={slo_tpot_ms:.0f}ms, "
          f">={min_att:.0%} of requests): slab {slab_max}  "
          f"paged {paged_max}  ratio {ratio:.2f}x  -> {out_path}")
    return 0 if bench["ok"] else 1


class _VirtualFleet:
    """Virtual-time harness for fleet benches on ONE host (ISSUE 13).

    The router loop is single-threaded, so on this CPU every replica's
    compute serializes — wall-clock latencies of an N-replica fleet
    measure one core, not N chips. This harness models the parallel
    fleet the way BENCH_autoscale's paced tick did, but PER REPLICA:
    a shared virtual clock is injected into the router and every
    engine, each replica only steps when the virtual clock reaches its
    own `due` time, and a completed step advances that replica's due by
    its own MEASURED wall cost (floored at `tick_floor_s`). Replicas
    thus tick at their own real speed in parallel virtual time — a
    prefill-class replica grinding a 64-token chunk has a slow tick,
    the decode replicas next to it keep their fast ones — while every
    TTFT/TPOT is stamped from real measured compute. Router host work
    (dispatch, page transfers, trace absorption) is charged to the
    virtual clock SERIALLY — conservative: it bills the disaggregated
    topology for every byte it ships.

    `transfer_on_replicas=True` (the KV CDN bench) refines that one
    charge: KV page export/import wall moves onto the PARTICIPATING
    replica's own timeline instead of the serial router remainder — a
    transfer is a source<->dest DMA occupying those chips' bandwidth,
    not a fleet-wide stall. The serial default stays for the disagg
    bench (conservative against its transfer-heavy topologies)."""

    def __init__(self, tick_floor_s=0.002, transfer_on_replicas=False):
        self.vt = [0.0]
        self.due = {}
        self.tick_floor_s = float(tick_floor_s)
        self._pass_wall = 0.0
        self.transfer_on_replicas = bool(transfer_on_replicas)

    def clock(self):
        return self.vt[0]

    def gate(self, router):
        for rep in router.replicas:
            orig = rep.step

            def gated(rep=rep, orig=orig):
                if self.vt[0] + 1e-12 < self.due.get(rep.replica_id,
                                                     0.0):
                    return []
                t0 = time.perf_counter()
                fins = orig()
                w = time.perf_counter() - t0
                self._pass_wall += w
                self.due[rep.replica_id] = self.vt[0] + max(
                    self.tick_floor_s, w)
                return fins

            rep.step = gated
            if not self.transfer_on_replicas:
                continue
            for op in ("export_chain", "import_pages"):
                if not hasattr(rep, op):
                    continue

                def charged(*a, _o=getattr(rep, op), _rep=rep, **kw):
                    t0 = time.perf_counter()
                    try:
                        return _o(*a, **kw)
                    finally:
                        w = time.perf_counter() - t0
                        self._pass_wall += w
                        self.due[_rep.replica_id] = max(
                            self.due.get(_rep.replica_id, 0.0),
                            self.vt[0]) + w

                setattr(rep, op, charged)
        return router

    def step(self, router):
        """Advance virtual time to the earliest due replica, run one
        router pass, and charge the router's own host remainder."""
        if self.due:
            self.vt[0] = max(self.vt[0], min(self.due.values()))
        self._pass_wall = 0.0
        t0 = time.perf_counter()
        fins = router.step()
        host = time.perf_counter() - t0 - self._pass_wall
        self.vt[0] += max(0.0, host)
        return fins


def disagg_bench(args):
    """BENCH_disagg.json (ISSUE 13 acceptance): at EQUAL total replica
    count, sweep the prefill:decode split (0 = homogeneous) over a
    long-prompt-injection workload and binary-search each topology's
    max sustainable closed-loop concurrency at the TTFT/TPOT SLO.
    Acceptance: the best disaggregated split beats the homogeneous
    fleet's frontier by >= 1.2x at >= min_attainment, AND the decode
    TPOT p99 of SHORT requests — the co-tenants a long prompt would
    steal ticks from — never degrades beyond the homogeneous fleet's,
    compared at EQUAL LOAD (both fleets at the homogeneous max; each
    at its own max would conflate batch-size cost with interference).

    `--smoke` is the tier-1 CI path: one tiny homogeneous-vs-1:1 pair
    at fixed concurrency, asserting the MECHANICS (handoffs happened,
    every request served, transfer counters moved) without the
    acceptance bar or the search — seconds, not minutes."""
    import json as _json

    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Router

    smoke = "smoke" in args
    seed = int(args.get("seed", 0))
    n_total = int(args.get("n_replicas", 4 if not smoke else 2))
    n_slots = int(args.get("n_slots", 8 if not smoke else 4))
    page_size = int(args.get("page_size", 16))
    prefill_chunk = int(args.get("prefill_chunk", 64))
    kv_budget = int(args.get("kv_budget_tokens",
                             6144 if not smoke else 2048))
    block_size = int(args.get("block_size", 512 if not smoke else 256))
    max_seq = int(args.get("max_seq_len", block_size))
    long_lo = int(args.get("long_lo", 224 if not smoke else 96))
    long_hi = int(args.get("long_hi", 352 if not smoke else 128))
    short_lo = int(args.get("short_lo", 16))
    short_hi = int(args.get("short_hi", 48))
    long_frac = float(args.get("long_frac", 0.2))
    max_new = int(args.get("max_new_tokens", 24 if not smoke else 8))
    n_requests = int(args.get("bench_requests", 48 if not smoke else 10))
    max_conc = int(args.get("max_concurrency", 24 if not smoke else 3))
    slo_ttft_ms = float(args.get("slo_ttft_ms", 2000.0))
    slo_tpot_ms = float(args.get("slo_tpot_ms", 60.0))
    min_att = float(args.get("min_attainment", 0.9))
    out_path = args.get("out", "BENCH_disagg.json")
    splits = ([0, 1] if smoke else
              [int(s) for s in args.get(
                  "splits", ",".join(str(i)
                                     for i in range(n_total - 1))
              ).split(",")])
    assert long_hi + max_new <= max_seq <= block_size

    model = GPT(GPTConfig(
        block_size=block_size, vocab_size=int(args.get("vocab_size", 256)),
        n_layer=int(args.get("n_layer", 4 if not smoke else 1)),
        n_head=int(args.get("n_head", 2)),
        n_embd=int(args.get("n_embd", 128 if not smoke else 32)),
        dropout=0.0, bias=True, attn_impl="xla"), rngs=nnx.Rngs(seed))
    V = model.config.vocab_size

    def mk_prompt(rng):
        """UNIQUE prompts: prefix sharing must stay on (it is the
        import splice mechanism) without repeated prompts short-
        circuiting the very prefill work the bench measures."""
        if rng.random() < long_frac:
            n = int(rng.integers(long_lo, long_hi + 1))
        else:
            n = int(rng.integers(short_lo, short_hi + 1))
        return [int(t) for t in rng.integers(0, V, n)]

    def run_trial(n_prefill, n_conc, label, n_req=None):
        n_req = n_requests if n_req is None else n_req
        reg = MetricsRegistry()
        vf = _VirtualFleet(tick_floor_s=float(args.get("tick_floor_ms",
                                                       2.0)) / 1e3)
        router = Router(
            model, n_replicas=n_total, n_slots=n_slots,
            max_seq_len=max_seq, registry=reg, seed=seed,
            clock=vf.clock, n_prefill=n_prefill,
            disagg_min_prompt=prefill_chunk,
            engine_kwargs={"kv_impl": "paged", "page_size": page_size,
                           "n_pages": kv_budget // page_size,
                           "prefill_chunk": prefill_chunk})
        vf.gate(router)
        rng = np.random.default_rng(seed)
        # warmup: every bucket (short + long + chunk ladder) compiles
        # on every replica before the measured window; page caches are
        # churned by unique prompts, so no measured prefill is skipped
        for _ in range(2 * n_total):
            router.submit(mk_prompt(rng), max_new_tokens=max_new,
                          temperature=1.0, top_k=None)
            router.submit([int(t) for t in rng.integers(0, V, long_hi)],
                          max_new_tokens=max_new, temperature=1.0,
                          top_k=None)
        while router.open_requests or router._pending:
            vf.step(router)
        submitted = 0
        done = []
        n_prompt_of = {}
        while len(done) < n_req:
            while (submitted < n_req
                   and submitted - len(done) < n_conc):
                p = mk_prompt(rng)
                rid = router.submit(p, max_new_tokens=max_new,
                                    temperature=1.0, top_k=None)
                n_prompt_of[rid] = len(p)
                submitted += 1
            done.extend(vf.step(router))
        att = slo_attainment(done, slo_ttft_ms=slo_ttft_ms,
                             slo_tpot_ms=slo_tpot_ms)
        ttfts = [f.ttft_ms for f in done if f.ttft_ms is not None]
        short_tpots = [f.tpot_ms for f in done
                       if f.n_out > 1
                       and n_prompt_of.get(f.req_id, 0) < prefill_chunk]
        counters = reg.snapshot()["counters"]
        stats = {
            "n_conc": n_conc, "attainment": att,
            "ttft_p50_ms": _pct(ttfts, 0.50),
            "ttft_p99_ms": _pct(ttfts, 0.99),
            "short_tpot_p50_ms": _pct(short_tpots, 0.50),
            "short_tpot_p99_ms": _pct(short_tpots, 0.99),
            "kv_transfers": counters.get("kv_transfers", 0.0),
            "kv_pages_exported": counters.get("kv_pages_exported", 0.0),
            "kv_transfer_bytes": counters.get("kv_transfer_bytes", 0.0),
        }
        ok = att is not None and att >= min_att
        print(f"[disagg:{label}] n={n_conc:3d}  attainment "
              f"{att:6.1%}  ttft p99 {stats['ttft_p99_ms']:8.1f} ms  "
              f"short tpot p99 {stats['short_tpot_p99_ms']:7.2f} ms  "
              f"transfers {stats['kv_transfers']:.0f}")
        router.close()
        return ok, stats, done

    if smoke:
        # the CI fast path (tier-1 under JAX_PLATFORMS=cpu): assert the
        # MECHANICS — handoffs flowed, nothing was lost — at tiny scale.
        # `smoke_splits` lets CI run just the disagg cell (one fresh
        # fleet's compiles); the CLI default also runs the homogeneous
        # cell for the eyeball comparison.
        st1 = None
        for k in [int(s) for s in
                  args.get("smoke_splits", "0,1").split(",")]:
            ok_, st_, done_ = run_trial(k, max_conc,
                                        f"{k}-of-{n_total}")
            assert len(done_) == n_requests
            assert all(f.finish_reason == "length" for f in done_), (
                [f.finish_reason for f in done_])
            if k > 0:
                st1 = st_
        if st1 is not None:
            assert st1["kv_transfers"] > 0, "no handoff happened in smoke"
            assert st1["kv_pages_exported"] > 0
        print("[disagg] smoke ok: handoffs flowed, every request served")
        return 0

    def frontier(n_prefill):
        label = f"{n_prefill}:{n_total - n_prefill}"
        trials = []
        ok1, st, _ = run_trial(n_prefill, 1, label)
        trials.append(st)
        if not ok1:
            return {"max_sustainable_concurrency": 0, "trials": trials}
        lo, hi = 1, max_conc
        while lo < hi:
            mid = (lo + hi + 1) // 2
            ok, st, _ = run_trial(n_prefill, mid, label)
            trials.append(st)
            if ok:
                lo = mid
            else:
                hi = mid - 1
        at_max = next((t for t in trials if t["n_conc"] == lo), trials[0])
        return {"max_sustainable_concurrency": lo, "trials": trials,
                "at_max": at_max}

    results = {}
    for k in splits:
        results[f"prefill_{k}"] = frontier(k)
    homo = results.get("prefill_0")
    assert homo is not None, "the split sweep must include 0 (baseline)"
    homo_max = homo["max_sustainable_concurrency"]
    best_k, best = max(
        ((k, r) for k, r in results.items() if k != "prefill_0"),
        key=lambda kr: kr[1]["max_sustainable_concurrency"])
    ratio = (best["max_sustainable_concurrency"] / homo_max
             if homo_max else float("inf"))
    # the long-prompt-injection TPOT guard, at EQUAL LOAD: comparing
    # each fleet at its OWN max would conflate batch-size cost (TPOT
    # grows with live slots) with the interference this guard isolates
    # — whether co-located long-prompt prefill steals decode ticks
    # from short co-tenants. Both fleets serve the identical workload
    # at the homogeneous fleet's own best operating point (its max
    # sustainable concurrency), with 2x the requests so the short-
    # request p99 isn't a single-sample statistic.
    guard_n = max(1, homo_max)
    k_best = int(best_k.split("_")[1])
    _, homo_guard, _ = run_trial(0, guard_n, f"guard:0:{n_total}",
                                 n_req=2 * n_requests)
    _, best_guard, _ = run_trial(
        k_best, guard_n, f"guard:{k_best}:{n_total - k_best}",
        n_req=2 * n_requests)
    homo_tpot = homo_guard["short_tpot_p99_ms"]
    best_tpot = best_guard["short_tpot_p99_ms"]
    tpot_ok = not (best_tpot > homo_tpot)  # NaN-tolerant: never worse
    bench = {
        "kind": "disagg_sweep",
        "config": {
            "seed": seed, "n_replicas": n_total, "n_slots": n_slots,
            "block_size": block_size, "page_size": page_size,
            "prefill_chunk": prefill_chunk,
            "kv_budget_tokens": kv_budget,
            "long_prompt_tokens": [long_lo, long_hi],
            "short_prompt_tokens": [short_lo, short_hi],
            "long_frac": long_frac, "max_new_tokens": max_new,
            "n_requests": n_requests, "slo_ttft_ms": slo_ttft_ms,
            "slo_tpot_ms": slo_tpot_ms, "min_attainment": min_att,
            "timing_model": (
                "virtual-time parallel-fleet replay on one host: each "
                "replica steps when the shared virtual clock reaches "
                "its due time and advances it by its own MEASURED step "
                "wall (floor tick_floor); router host work incl. page "
                "transfers charged serially — conservative against "
                "the disaggregated topologies, which pay for every "
                "byte shipped. Latencies are virtual-clock ms over "
                "real measured compute."),
        },
        **results,
        "homogeneous_max": homo_max,
        "best_split": best_k,
        "best_split_max": best["max_sustainable_concurrency"],
        "concurrency_ratio": ratio,
        "tpot_guard": {
            "n_conc": guard_n, "n_requests": 2 * n_requests,
            "note": ("equal-load long-prompt-injection guard: both "
                     "fleets at the homogeneous fleet's max "
                     "sustainable concurrency"),
            "homogeneous": homo_guard, "best_split": best_guard},
        "short_tpot_p99_ms": {"homogeneous": homo_tpot,
                              "best_split": best_tpot},
        "ok": bool(homo_max > 0 and ratio >= 1.2 and tpot_ok),
    }
    with open(out_path, "w") as f:
        _json.dump(bench, f, indent=1)
    print(f"[disagg] max sustainable concurrency at SLO: "
          + "  ".join(f"{k}={r['max_sustainable_concurrency']}"
                      for k, r in results.items()))
    print(f"[disagg] best split {best_k}: {ratio:.2f}x homogeneous; "
          f"short-tpot p99 {best_tpot:.2f} vs {homo_tpot:.2f} ms "
          f"-> {out_path} (ok={bench['ok']})")
    return 0 if bench["ok"] else 1


def autoscale_bench(args):
    """BENCH_autoscale.json (ISSUE 12 acceptance): on the seeded
    diurnal shape, the autoscaled fleet must meet --min_attainment at
    >= 25% fewer replica-seconds than the smallest STATIC fleet that
    also meets it. Every cell replays the same seeded arrival/prompt
    schedule; every replica (static or spawned) pre-warms its compile
    caches before taking work, so no cell serves compiles to users and
    the comparison is pure capacity economics: a static fleet must be
    provisioned for the diurnal PEAK all day, the autoscaled fleet
    follows the curve."""
    import json as _json

    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.obs.trace import Tracer
    from avenir_tpu.serve import Router
    from avenir_tpu.serve.autoscale import Autoscaler, SLOEngine

    seed = int(args.get("seed", 0))
    n_requests = int(args.get("n_requests", 1248))
    rate = float(args.get("rate", 13.0))
    period_s = float(args.get("period_s", 48.0))
    amp = float(args.get("amp", 0.85))
    n_slots = int(args.get("n_slots", 2))
    max_new = int(args.get("max_new_tokens", 8))
    max_prompt = int(args.get("max_prompt", 8))
    slo_ttft_ms = float(args.get("slo_ttft_ms", 1000.0))
    slo_tpot_ms = float(args.get("slo_tpot_ms", 250.0))
    min_att = float(args.get("min_attainment", 0.9))
    max_static = int(args.get("max_static", 3))
    # the elastic fleet gets the same ceiling as the static sweep: the
    # comparison is pure follow-the-curve economics (pass --autoscale
    # above max_static to let it burst past the best static size —
    # useful when ramp backlogs need fast drain, not at this SLO slack)
    autoscale_max = int(args.get("autoscale", max_static))
    auto_start = int(args.get("auto_start", 2))
    slo_window_s = float(args.get("slo_window_s", 6.0))
    max_seq_len = int(args.get("max_seq_len", 16))
    assert max_prompt + max_new <= max_seq_len
    # fixed decode-tick cadence: on a real chip the batched decode tick
    # is bandwidth-bound and ~constant per replica; on this CPU bench
    # the tiny model's compute fits far inside it, so each fleet-loop
    # pass sleeps out the remainder of --tick_ms. Capacity is then
    # slots x ticks — it SCALES with fleet size (the thing the bench
    # measures) instead of being capped by the one host CPU — while
    # every TTFT/TPOT stays honest wall time
    tick_s = float(args.get("tick_ms", 25.0)) / 1e3
    out_path = args.get("out", "BENCH_autoscale.json")

    model = GPT(GPTConfig(
        block_size=int(args.get("block_size", 64)), vocab_size=256,
        n_layer=int(args.get("n_layer", 1)), n_head=2,
        n_embd=int(args.get("n_embd", 32)),
        dropout=0.0, bias=True, attn_impl="xla"), rngs=nnx.Rngs(seed))

    mix = np.random.default_rng(seed)
    arrivals, load_cfg = gen_arrivals("diurnal", mix, n_requests, rate,
                                      period_s=period_s, amp=amp)
    prompts = [
        [int(t) for t in mix.integers(
            0, 256, int(mix.integers(2, max_prompt + 1)))]
        for _ in range(n_requests)
    ]

    def run_cell(n_static=None, autoscale=False):
        reg = MetricsRegistry()
        tracer = Tracer(registry=reg) if autoscale else None
        router = Router(model, n_replicas=(n_static or auto_start),
                        n_slots=n_slots, max_seq_len=max_seq_len,
                        registry=reg, seed=seed, tracer=tracer,
                        engine_kwargs={"prewarm": True})
        scaler = None
        if autoscale:
            slo = SLOEngine(slo_ttft_ms=slo_ttft_ms,
                            slo_tpot_ms=slo_tpot_ms,
                            target_attainment=min_att,
                            window_s=slo_window_s, registry=reg)
            scaler = Autoscaler(
                router, slo, min_replicas=1,
                max_replicas=autoscale_max,
                up_queue_wait_ms=float(args.get("up_queue_wait_ms",
                                                slo_ttft_ms * 0.35)),
                up_stable_s=float(args.get("up_stable_s", 0.5)),
                down_stable_s=float(args.get("down_stable_s", 2.0)),
                cooldown_s=float(args.get("cooldown_s", 1.25)),
                down_util=float(args.get("down_util", 0.7)),
                spawn_async=True)
        t0 = time.perf_counter()
        submitted = 0
        done = []
        while len(done) < n_requests:
            now = time.perf_counter() - t0
            while submitted < n_requests and arrivals[submitted] <= now:
                router.submit(prompts[submitted],
                              max_new_tokens=max_new,
                              temperature=1.0, top_k=None)
                submitted += 1
            if router.open_requests or router._pending:
                t_step = time.perf_counter()
                fins = router.step()
                done.extend(fins)
                if scaler is not None:
                    scaler.observe(fins)
                lag = tick_s - (time.perf_counter() - t_step)
                if lag > 0:
                    time.sleep(lag)  # the paced tick cadence
            elif submitted < n_requests:
                time.sleep(min(tick_s,
                               max(0.0, arrivals[submitted] - now)))
            if scaler is not None:
                scaler.poll()
        wall = time.perf_counter() - t0
        if scaler is not None:
            scaler.poll()
        if scaler is not None:
            scaler.close()  # reap any still-warming background spawn
        att = slo_attainment(done, slo_ttft_ms=slo_ttft_ms,
                             slo_tpot_ms=slo_tpot_ms)
        ttfts = [f.ttft_ms for f in done if f.ttft_ms is not None]
        counters = reg.snapshot()["counters"]
        cell = {
            "attainment": att, "wall_s": round(wall, 3),
            "ttft_p50_ms": _pct(ttfts, 0.50),
            "ttft_p99_ms": _pct(ttfts, 0.99),
        }
        if autoscale:
            cell["replica_seconds"] = counters.get(
                "fleet_replica_seconds", 0.0)
            cell["scale_up"] = counters.get("scale_up", 0.0)
            cell["scale_down"] = counters.get("scale_down", 0.0)
            cell["prewarm_ticks"] = counters.get("prewarm_ticks", 0.0)
            cell["decisions"] = [
                {"t_s": round(d.t - t0, 3), "action": d.action,
                 "reason": d.reason, "from_size": d.from_size,
                 "to_size": d.to_size, "evidence": d.evidence}
                for d in scaler.decisions
            ]
        else:
            # a static fleet holds n chips for the whole serving window
            cell["replica_seconds"] = n_static * wall
        router.close()
        name = "auto" if autoscale else f"static{n_static}"
        print(f"[autoscale_bench:{name}] attainment "
              f"{(att if att is not None else float('nan')):6.1%}  "
              f"replica-seconds {cell['replica_seconds']:7.1f}  "
              f"ttft p99 {cell['ttft_p99_ms']:7.0f} ms")
        return cell

    cells = {}
    for nrep in range(1, max_static + 1):
        cells[f"static_{nrep}"] = run_cell(n_static=nrep)
    cells["autoscale"] = run_cell(autoscale=True)

    ok_static = sorted(
        (int(k.split("_")[1]), c) for k, c in cells.items()
        if k.startswith("static_") and c["attainment"] is not None
        and c["attainment"] >= min_att)
    auto = cells["autoscale"]
    smallest = ok_static[0] if ok_static else None
    savings = None
    if smallest is not None and smallest[1]["replica_seconds"] > 0:
        savings = 1.0 - (auto["replica_seconds"]
                         / smallest[1]["replica_seconds"])
    ok = (auto["attainment"] is not None
          and auto["attainment"] >= min_att
          and savings is not None and savings >= 0.25)
    bench = {
        "kind": "autoscale_bench",
        "config": {
            "seed": seed, "n_requests": n_requests,
            **load_cfg,
            "n_slots": n_slots, "max_new_tokens": max_new,
            "max_prompt": max_prompt, "slo_ttft_ms": slo_ttft_ms,
            "slo_tpot_ms": slo_tpot_ms, "min_attainment": min_att,
            "slo_window_s": slo_window_s, "max_static": max_static,
            "autoscale_max": autoscale_max, "auto_start": auto_start,
            "max_seq_len": max_seq_len,
            "tick_ms": tick_s * 1e3,
            "tick_note": (
                "every fleet-loop pass is paced to tick_ms (the "
                "bandwidth-bound decode tick of a real replica; the "
                "tiny CPU model's compute fits inside it, the "
                "remainder is slept) so capacity scales with slots x "
                "replicas instead of the one host CPU; latencies are "
                "real wall time"),
            "replica_seconds_note": (
                "static cells bill n_replicas x wall; the autoscale "
                "cell bills the fleet_replica_seconds counter "
                "(per-poll dt x non-dead replicas, draining retirees "
                "included) — same clock, same serving window"),
        },
        "cells": cells,
        "smallest_static_meeting_slo": (smallest[0] if smallest
                                        else None),
        "autoscale_attainment": auto["attainment"],
        "replica_second_savings": savings,
        "ok": ok,
    }
    with open(out_path, "w") as f:
        _json.dump(bench, f, indent=1)
    print(f"[autoscale_bench] smallest static meeting SLO: "
          f"{smallest[0] if smallest else 'none'}  "
          f"autoscale attainment "
          f"{(auto['attainment'] or float('nan')):.1%}  "
          f"replica-second savings "
          f"{(savings if savings is not None else float('nan')):.1%}"
          f"  -> {out_path} (ok={ok})")
    return 0 if ok else 1


def rollout_bench(args):
    """BENCH_rollout.json (ISSUE 20 acceptance): under seeded Poisson
    load on a 3-replica fleet, (A) a CLEAN rolling weight rollout
    (canary -> rolling swap) converges with ZERO lost requests inside a
    bounded version-mixing window, then (B) a POISONED canary
    (serve_step_degrade: each fire adds a permanent +2 ms to one
    replica's busy steps — armed the moment the canary starts serving)
    trips the drift detectors and AUTO-ROLLS-BACK, also zero-lost, with
    the whole fleet converged back on the pre-campaign version.
    Headline (PERF ledger): rollback latency, poison armed ->
    rollback_begin decision. `--smoke` is the tier-1 twin: same two
    campaigns, smaller load, tighter detector windows."""
    import json as _json

    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.obs.trace import Tracer
    from avenir_tpu.serve import Router
    from avenir_tpu.utils.faults import FaultInjector, set_injector

    smoke = "smoke" in args
    seed = int(args.get("seed", 0))
    rate = float(args.get("rate", 18.0 if smoke else 24.0))
    n_slots = int(args.get("n_slots", 2))
    n_replicas = int(args.get("n_replicas", 3))
    max_new = int(args.get("max_new_tokens", 6))
    max_prompt = int(args.get("max_prompt", 8))
    max_seq_len = int(args.get("max_seq_len", 16))
    tick_s = float(args.get("tick_ms", 20.0)) / 1e3
    window_s = float(args.get("window_s", 0.3 if smoke else 0.5))
    max_mixing_s = float(args.get("max_mixing_s", 45.0))
    # poison budget: n fires split across every stepping replica's
    # consults — bounded so the post-rollback fleet stays serviceable
    poison_n = int(args.get("poison_n", 45 if smoke else 75))
    rollback_bound_s = float(args.get("rollback_bound_s", 20.0))
    timeout_s = float(args.get("timeout_s", 90.0 if smoke else 180.0))
    warm_n = int(args.get("warm_n", 12 if smoke else 32))
    cap = int(args.get("max_requests", 1200 if smoke else 4000))
    out_path = args.get("out", "BENCH_rollout.json")

    model = GPT(GPTConfig(
        block_size=int(args.get("block_size", 64)), vocab_size=256,
        n_layer=1, n_head=2, n_embd=int(args.get("n_embd", 32)),
        dropout=0.0, bias=True, attn_impl="xla"), rngs=nnx.Rngs(seed))
    state_v2 = nnx.split(GPT(model.config, rngs=nnx.Rngs(seed + 1)))[1]
    state_v3 = nnx.split(GPT(model.config, rngs=nnx.Rngs(seed + 2)))[1]

    reg = MetricsRegistry()
    tracer = Tracer(registry=reg)
    router = Router(model, n_replicas=n_replicas, n_slots=n_slots,
                    max_seq_len=max_seq_len, registry=reg, seed=seed,
                    tracer=tracer, engine_kwargs={"prewarm": True})

    rng = np.random.default_rng(seed)
    prompts = [
        [int(t) for t in rng.integers(
            0, 256, int(rng.integers(2, max_prompt + 1)))]
        for _ in range(256)
    ]
    # faster verdicts than the production defaults: the bench pays wall
    # time per detector window, and the poison signal is huge (tens of
    # ms on a ~tick-bound baseline), so shorter histories stay sound
    det_params = {"ttft_drift": {"min_windows": 6, "sustain": 2},
                  "tpot_drift": {"min_windows": 6, "sustain": 2}}
    if smoke:
        # tiny fleets amplify the canary's rebalancing bias (a 2-replica
        # smoke fleet hands the empty rejoining canary ~half the queue),
        # and the poison signal is ~10x — a higher rel floor keeps the
        # clean campaign clean without costing the drill any teeth
        for d in det_params.values():
            d["min_rel"] = 0.8
    ro_kw = dict(window_s=window_s, max_mixing_s=max_mixing_s,
                 baseline_min_requests=8, canary_min_requests=8,
                 detector_params=det_params, echo=lambda _s: None)

    t0 = time.perf_counter()
    next_arrival, submitted, done = 0.0, 0, []
    stage = "warmup"  # -> "A" -> "B" -> "drain"
    ro_a = ro_b = None
    t_poison = t_rollback = None
    prev_inj = None
    timed_out = False
    try:
        while True:
            now = time.perf_counter() - t0
            if now > timeout_s:
                timed_out = True
                break
            if stage != "drain":
                while next_arrival <= now and submitted < cap:
                    router.submit(prompts[submitted % len(prompts)],
                                  max_new_tokens=max_new,
                                  temperature=1.0, top_k=None)
                    submitted += 1
                    next_arrival += float(rng.exponential(1.0 / rate))
            t_step = time.perf_counter()
            done.extend(router.step())
            lag = tick_s - (time.perf_counter() - t_step)
            if lag > 0:
                time.sleep(lag)
            if stage == "warmup" and len(done) >= warm_n:
                ro_a = router.rollout("v2", state=state_v2, **ro_kw)
                stage = "A"
            elif stage == "A" and not ro_a.active:
                # a LONGER canary hold for the poisoned campaign: the
                # verdict window must comfortably contain the detector
                # decision (min_windows of canary data + sustain
                # checks) — a trip aborts the hold immediately, so the
                # extra headroom costs nothing on the rollback path
                ro_b = router.rollout(
                    "v3", state=state_v3,
                    **{**ro_kw, "canary_hold_s": 24.0 * window_s})
                stage = "B"
            elif stage == "B":
                if t_poison is None and ro_b.phase == "canary":
                    # poison lands the moment the canary starts
                    # serving the new version — the ISSUE 14
                    # train_step_degrade pattern, serve-side
                    prev_inj = set_injector(FaultInjector(
                        f"serve_step_degrade:p=1:n={poison_n}"))
                    t_poison = time.perf_counter() - t0
                if (t_rollback is None
                        and ro_b.phase == "rolling_back"):
                    t_rollback = time.perf_counter() - t0
                if not ro_b.active:
                    stage = "drain"
            elif stage == "drain" and not router.open_requests \
                    and not router._pending:
                break
    finally:
        if prev_inj is not None:
            set_injector(prev_inj)
        router.close()

    lost = submitted - len(done)
    mixing_a = ro_a.mixing_s if ro_a is not None else None
    rollback_latency_s = (round(t_rollback - t_poison, 3)
                          if t_rollback is not None
                          and t_poison is not None else None)
    end_versions = sorted({getattr(r, "weight_version", "0")
                           for r in router.replicas})
    ok = (not timed_out and lost == 0
          and ro_a is not None and not ro_a.rolled_back
          and ro_a.phase == "done"
          and mixing_a is not None and mixing_a <= max_mixing_s
          and ro_b is not None and ro_b.rolled_back
          and ro_b.phase == "done"
          and ro_b.rollback_reason == "canary_anomaly"
          and end_versions == ["v2"]
          and rollback_latency_s is not None
          and rollback_latency_s <= rollback_bound_s)

    # the decision log as tools/fleet_report.py renders it — the same
    # `rollout` trace events, summarized by the same code path
    try:
        from fleet_report import summarize_fleet  # python tools/serve_bench.py
    except ImportError:
        from tools.fleet_report import summarize_fleet  # imported from tests

    fleet = summarize_fleet(
        tracer.events(), {"counters": reg.snapshot()["counters"]})
    counters = reg.snapshot()["counters"]
    bench = {
        "kind": "rollout_bench",
        "smoke": smoke,
        "config": {
            "seed": seed, "rate": rate, "n_replicas": n_replicas,
            "n_slots": n_slots, "max_new_tokens": max_new,
            "max_prompt": max_prompt, "tick_ms": tick_s * 1e3,
            "window_s": window_s, "max_mixing_s": max_mixing_s,
            "poison_n": poison_n,
            "rollback_bound_s": rollback_bound_s,
            "detector_params": det_params,
        },
        "requests": {"submitted": submitted, "finished": len(done),
                     "lost": lost},
        "campaigns": {
            "clean": None if ro_a is None else {
                **ro_a.status(), "decisions": ro_a.decisions},
            "poisoned": None if ro_b is None else {
                **ro_b.status(), "decisions": ro_b.decisions,
                "t_poison_s": t_poison,
                "t_rollback_s": t_rollback,
                "rollback_latency_s": rollback_latency_s},
        },
        "end_versions": end_versions,
        "counters": {k: counters.get(k) for k in
                     ("rollouts", "rollbacks", "canary_anomalies",
                      "serve_failovers")},
        "fleet_report": {"rollout_decisions": fleet["rollouts"]},
        "timed_out": timed_out,
        "ok": ok,
    }
    with open(out_path, "w") as f:
        _json.dump(bench, f, indent=1)
    print(f"[rollout_bench] lost {lost}/{submitted}  "
          f"mixing(clean) "
          f"{(mixing_a if mixing_a is not None else float('nan')):.2f}s"
          f"  rollback latency "
          f"{(rollback_latency_s if rollback_latency_s is not None else float('nan')):.2f}s"
          f"  end versions {end_versions}  -> {out_path} (ok={ok})")
    return 0 if ok else 1


def kv_cdn_bench(args):
    """BENCH_kv_cdn.json (ISSUE 17 acceptance): multi-tenant shared-
    prefix workload through `Router(affinity=...)` on/off at EQUAL
    CHIPS. N tenants each own a system prompt (the shared prefix);
    per-tenant Poisson schedules (gen_arrivals) merge into one global
    arrival order, so tenants interleave the way N independent
    customers actually hit a fleet. The page pool is sized so ONE
    replica cannot hold every tenant's prefix chain at once — blind
    routing spreads each tenant over all replicas and the LRU churns
    prefixes out from under their own traffic, while affinity
    concentrates each tenant where its chain already lives and peer
    pulls ship the stragglers (the KV CDN).

    Two headline cells, both at identical fleet shape:
      frontier  closed-loop binary search for max sustainable
                concurrency at the TTFT/TPOT SLO (same search as the
                paged/disagg sweeps), per affinity setting
      probe     OPEN-loop merged-Poisson arrivals at --rate on the
                virtual clock, per affinity setting — TTFT p99 under
                real interleaved arrivals, plus the reuse-audit
                partition (missed_reuse_frac) the PERF ledger bands

    ok requires affinity to beat blind on BOTH headline metrics and
    the affinity probe's missed_reuse_frac to land materially below
    the blind baseline band (PERF_LEDGER.json's 0.112 row)."""
    import json as _json

    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Router

    seed = int(args.get("seed", 0))
    n_repl = int(args.get("n_replicas", 3))
    n_slots = int(args.get("n_slots", 3))
    n_tenants = int(args.get("n_tenants", 6))
    page_size = int(args.get("page_size", 16))
    n_pages = int(args.get("n_pages", 132))
    prefill_chunk = int(args.get("prefill_chunk", 32))
    block_size = int(args.get("block_size", 512))
    sys_prompt = int(args.get("system_prompt_tokens", 448))
    tail_lo = int(args.get("tail_lo", 8))
    tail_hi = int(args.get("tail_hi", 24))
    max_new = int(args.get("max_new_tokens", 8))
    n_requests = int(args.get("bench_requests", 48))
    max_conc = int(args.get("max_concurrency", 8))
    rate = float(args.get("rate", 26.0))  # merged offered req/s, probe
    slo_ttft_ms = float(args.get("slo_ttft_ms", 250.0))
    slo_tpot_ms = float(args.get("slo_tpot_ms", 60.0))
    min_att = float(args.get("min_attainment", 0.9))
    out_path = args.get("out", "BENCH_kv_cdn.json")
    max_seq = sys_prompt + tail_hi + max_new
    assert max_seq <= block_size
    # the contention knob: every tenant's chain cached at once must NOT
    # fit one replica next to its live working set, or blind routing
    # never churns and there is nothing for affinity to win
    pages_per_prefix = sys_prompt // page_size
    assert n_tenants * pages_per_prefix + n_slots * (
        max_seq + page_size - 1) // page_size > n_pages, (
        "pool too large: every tenant fits everywhere, the bench "
        "would measure nothing")

    model = GPT(GPTConfig(
        block_size=block_size, vocab_size=int(args.get("vocab_size", 256)),
        n_layer=int(args.get("n_layer", 4)),
        n_head=int(args.get("n_head", 2)),
        n_embd=int(args.get("n_embd", 128)),
        dropout=0.0, bias=True, attn_impl="xla"), rngs=nnx.Rngs(seed))
    V = model.config.vocab_size

    mix_rng = np.random.default_rng(seed)
    prefixes = [[int(t) for t in mix_rng.integers(0, V, sys_prompt)]
                for _ in range(n_tenants)]

    def compile_warmup():
        """Pay every XLA compile OUTSIDE the measured cells (the
        compile cache is process-wide): the prefill chunk ladder, the
        prefix-attached tail buckets, and the pull path's gather /
        scatter buckets. Without this, whichever cell FIRST touches a
        shape eats a multi-second compile straight into its p99 — and
        the pull shapes only ever fire in the affinity cell, so the
        comparison would charge compiles to one side."""
        from avenir_tpu.serve import Engine

        # max_seq_len must MATCH the cells: gather/scatter widths
        # bucket against max_pages_per_seq, so a mismatch leaves the
        # cells' shapes uncompiled and the warmup worthless
        kw = dict(kv_impl="paged", page_size=page_size, n_pages=n_pages,
                  prefill_chunk=prefill_chunk, max_seq_len=max_seq)
        a = Engine(model, n_slots=n_slots, registry=MetricsRegistry(),
                   **kw)
        b = Engine(model, n_slots=n_slots, registry=MetricsRegistry(),
                   **kw)
        rng = np.random.default_rng(seed + 9)
        w = [int(t) for t in rng.integers(0, V, sys_prompt)]
        # ladder + warm-attach buckets: first submit computes the
        # chain cold, the repeats attach it and compute only the tail
        for tail in sorted({tail_lo, (tail_lo + tail_hi) // 2,
                            tail_hi}):
            tl = [int(t) for t in rng.integers(0, V, tail)]
            a.submit(w + tl, max_new_tokens=max_new, temperature=1.0,
                     top_k=None)
            a.drain()
        # pull path: export/import chains at every power-of-2 bucket a
        # measured pull can hit (gather and scatter pad to buckets)
        for L in sorted({1, 2, 4, 8, 16, pages_per_prefix}):
            c = [int(t) for t in rng.integers(0, V,
                                              L * page_size + tail_lo)]
            a.submit(c, max_new_tokens=max_new, temperature=1.0,
                     top_k=None)
            a.drain()
            rec = a.export_chain([c[i * page_size:(i + 1) * page_size]
                                  for i in range(L)])
            if rec is not None:
                b.import_kv_pages(rec["tokens"], rec["arrays"],
                                  kv_dtype=rec["kv_dtype"])
        # attach over imported pages (the receiver's post-pull prefill)
        rec = a.export_chain([w[i * page_size:(i + 1) * page_size]
                              for i in range(pages_per_prefix)])
        b.import_kv_pages(rec["tokens"], rec["arrays"],
                          kv_dtype=rec["kv_dtype"])
        b.submit(w + [int(t) for t in rng.integers(0, V, tail_lo)],
                 max_new_tokens=max_new, temperature=1.0, top_k=None)
        b.drain()

    def tenant_order(n):
        """Merge per-tenant Poisson schedules into one arrival order
        (+ times for the open-loop probe) — seeded per tenant."""
        merged = []
        for t in range(n_tenants):
            arr, _ = gen_arrivals(
                "poisson", np.random.default_rng(seed * 997 + t), n,
                rate / n_tenants)
            merged.extend((float(a), t) for a in arr)
        merged.sort()
        return ([t for _, t in merged[:n]],
                [a for a, _ in merged[:n]])

    def mk_prompt(tenant, rng):
        tail = [int(t) for t in rng.integers(
            0, V, int(rng.integers(tail_lo, tail_hi + 1)))]
        return prefixes[tenant] + tail

    def build(affinity):
        reg = MetricsRegistry()
        vf = _VirtualFleet(tick_floor_s=float(args.get("tick_floor_ms",
                                                       2.0)) / 1e3,
                           transfer_on_replicas=True)
        router = Router(
            model, n_replicas=n_repl, n_slots=n_slots,
            max_seq_len=max_seq, registry=reg, seed=seed,
            clock=vf.clock, cache_telescope=True,
            affinity=bool(affinity),
            engine_kwargs={"kv_impl": "paged", "page_size": page_size,
                           "n_pages": n_pages,
                           "prefill_chunk": prefill_chunk})
        vf.gate(router)
        rng = np.random.default_rng(seed + 1)
        # replica warmup with UNIQUE throwaway prompts (the buckets /
        # chunk ladder on every replica), then a tenant warm pass
        # routed by the CELL'S OWN policy — the measured window is
        # steady state, and each cell earns exactly the warmth its
        # routing can earn: blind leaves every replica churning all
        # N tenants through one LRU, affinity shards them
        for _ in range(2 * n_repl):
            router.submit([int(t) for t in rng.integers(
                0, V, sys_prompt + tail_lo)], max_new_tokens=max_new,
                temperature=1.0, top_k=None)
        while router.open_requests or router._pending:
            vf.step(router)
        rngw = np.random.default_rng(seed + 4)
        for _ in range(2):
            for t in range(n_tenants):
                router.submit(mk_prompt(t, rngw),
                              max_new_tokens=max_new, temperature=1.0,
                              top_k=None)
            while router.open_requests or router._pending:
                vf.step(router)
        # counter baseline: the measured partition / pull ledger must
        # cover the window only, not the warm passes
        base = dict(reg.snapshot()["counters"])
        return router, reg, vf, base

    def cell_stats(done, reg, base, n_conc=None):
        att = slo_attainment(done, slo_ttft_ms=slo_ttft_ms,
                             slo_tpot_ms=slo_tpot_ms)
        ttfts = [f.ttft_ms for f in done if f.ttft_ms is not None]
        tpots = [f.tpot_ms for f in done if f.n_out > 1]
        c = {k: v - base.get(k, 0.0)
             for k, v in reg.snapshot()["counters"].items()}
        reused = c.get("prefix_tokens_reused", 0.0)
        missed = c.get("prefix_tokens_missed", 0.0)
        cold = c.get("prefix_tokens_cold", 0.0)
        total = reused + missed + cold
        st = {"attainment": att,
              "ttft_p50_ms": _pct(ttfts, 0.50),
              "ttft_p99_ms": _pct(ttfts, 0.99),
              "tpot_p50_ms": _pct(tpots, 0.50),
              "tpot_p99_ms": _pct(tpots, 0.99),
              "missed_reuse_frac": missed / total if total else 0.0,
              "prefix_tokens": {"reused": reused, "missed": missed,
                                "cold": cold},
              "affinity_hits": c.get("affinity_hits", 0.0),
              "prefix_pull_pages": c.get("prefix_pull_pages", 0.0),
              "prefix_pull_bytes": c.get("prefix_pull_bytes", 0.0),
              "prefix_pull_fallbacks": c.get("prefix_pull_fallbacks",
                                             0.0)}
        if n_conc is not None:
            st["n_conc"] = n_conc
        return st

    def closed_trial(affinity, n_conc):
        router, reg, vf, base = build(affinity)
        order, _ = tenant_order(n_requests)
        rng = np.random.default_rng(seed + 2)
        submitted, done = 0, []
        while len(done) < n_requests:
            while (submitted < n_requests
                   and submitted - len(done) < n_conc):
                router.submit(mk_prompt(order[submitted], rng),
                              max_new_tokens=max_new, temperature=1.0,
                              top_k=None)
                submitted += 1
            done.extend(vf.step(router))
        st = cell_stats(done, reg, base, n_conc=n_conc)
        label = "affinity" if affinity else "blind"
        print(f"[kv_cdn:{label}] n={n_conc:3d}  attainment "
              f"{st['attainment']:6.1%}  ttft p99 "
              f"{st['ttft_p99_ms']:7.1f} ms  missed "
              f"{st['missed_reuse_frac']:.3f}  pulls "
              f"{st['prefix_pull_pages']:.0f}p")
        router.close()
        ok = st["attainment"] is not None and st["attainment"] >= min_att
        return ok, st

    def frontier(affinity):
        trials = []
        ok1, st = closed_trial(affinity, 1)
        trials.append(st)
        if not ok1:
            return {"max_sustainable_concurrency": 0, "trials": trials}
        lo, hi = 1, max_conc
        while lo < hi:
            mid = (lo + hi + 1) // 2
            ok, st = closed_trial(affinity, mid)
            trials.append(st)
            if ok:
                lo = mid
            else:
                hi = mid - 1
        at_max = next((t for t in trials if t["n_conc"] == lo),
                      trials[0])
        return {"max_sustainable_concurrency": lo, "trials": trials,
                "at_max": at_max}

    def probe(affinity, n_req):
        """Open loop: submit on the merged Poisson schedule against
        the virtual clock — queue waits count against TTFT the way a
        real multi-tenant front door would see them."""
        router, reg, vf, base = build(affinity)
        order, times = tenant_order(n_req)
        rng = np.random.default_rng(seed + 3)
        t0 = vf.vt[0]
        submitted, done = 0, []
        while len(done) < n_req:
            if (submitted < n_req and not router.open_requests
                    and not router._pending):
                vf.vt[0] = max(vf.vt[0], t0 + times[submitted])
            while (submitted < n_req
                   and t0 + times[submitted] <= vf.vt[0] + 1e-9):
                router.submit(mk_prompt(order[submitted], rng),
                              max_new_tokens=max_new, temperature=1.0,
                              top_k=None)
                submitted += 1
            done.extend(vf.step(router))
        st = cell_stats(done, reg, base)
        label = "affinity" if affinity else "blind"
        print(f"[kv_cdn:probe:{label}] rate={rate:.0f}/s  attainment "
              f"{st['attainment']:6.1%}  ttft p99 "
              f"{st['ttft_p99_ms']:7.1f} ms  missed "
              f"{st['missed_reuse_frac']:.3f}  hits "
              f"{st['affinity_hits']:.0f}  pulls "
              f"{st['prefix_pull_pages']:.0f}p"
              f"/{st['prefix_pull_fallbacks']:.0f}fb")
        router.close()
        return st

    compile_warmup()
    results = {"blind": frontier(False), "affinity": frontier(True)}
    n_probe = 2 * n_requests
    probes = {"blind": probe(False, n_probe),
              "affinity": probe(True, n_probe)}
    blind_max = results["blind"]["max_sustainable_concurrency"]
    aff_max = results["affinity"]["max_sustainable_concurrency"]
    blind_p99 = probes["blind"]["ttft_p99_ms"]
    aff_p99 = probes["affinity"]["ttft_p99_ms"]
    missed_aff = probes["affinity"]["missed_reuse_frac"]
    missed_blind = probes["blind"]["missed_reuse_frac"]
    bench = {
        "kind": "kv_cdn_sweep",
        "config": {
            "seed": seed, "n_replicas": n_repl, "n_slots": n_slots,
            "n_tenants": n_tenants,
            "system_prompt_tokens": sys_prompt,
            "tail_tokens": [tail_lo, tail_hi],
            "max_new_tokens": max_new, "block_size": block_size,
            "page_size": page_size, "n_pages": n_pages,
            "prefill_chunk": prefill_chunk,
            "n_requests": n_requests, "probe_requests": n_probe,
            "rate": rate, "slo_ttft_ms": slo_ttft_ms,
            "slo_tpot_ms": slo_tpot_ms, "min_attainment": min_att,
            "timing_model": (
                "virtual-time parallel-fleet replay on one host "
                "(see BENCH_disagg.json): per-replica measured step "
                "cost, router host work charged serially"),
        },
        **results,
        "probe": probes,
        "max_sustainable_concurrency": {"blind": blind_max,
                                        "affinity": aff_max},
        "ttft_p99_ms": {"blind": blind_p99, "affinity": aff_p99},
        "missed_reuse_frac": {"blind": missed_blind,
                              "affinity": missed_aff},
        # the acceptance bar (ISSUE 17): affinity beats blind on BOTH
        # headlines at equal chips, and the residual missed-reuse
        # fraction lands materially below the blind telescope band
        # (PERF_LEDGER.json missed_reuse_frac row: 0.112)
        "ok": bool(aff_max > blind_max and aff_p99 < blind_p99
                   and missed_aff < 0.112 * 0.5),
    }
    with open(out_path, "w") as f:
        _json.dump(bench, f, indent=1)
    print(f"[kv_cdn] max sustainable concurrency: blind {blind_max}  "
          f"affinity {aff_max}; probe ttft p99 blind {blind_p99:.1f} "
          f"-> affinity {aff_p99:.1f} ms; missed_reuse_frac "
          f"{missed_blind:.3f} -> {missed_aff:.3f} -> {out_path} "
          f"(ok={bench['ok']})")
    return 0 if bench["ok"] else 1


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    if "kv_cdn" in args:
        sys.exit(kv_cdn_bench(args))
    if "sweep" in args:
        sys.exit(sweep(args))
    if "disagg" in args:
        sys.exit(disagg_bench(args))
    if "autoscale_bench" in args:
        sys.exit(autoscale_bench(args))
    if "rollout" in args:
        sys.exit(rollout_bench(args))
    n_requests = int(args.get("n_requests", 32))
    rate = float(args.get("rate", 16.0))  # mean arrivals per second
    n_slots = int(args.get("n_slots", 4))
    n_replicas = int(args.get("n_replicas", 1))
    batch_frac = float(args.get("batch_frac", 0.0))
    slo_ttft_ms = float(args.get("slo_ttft_ms", 500.0))
    slo_tpot_ms = float(args.get("slo_tpot_ms", 50.0))
    max_new = int(args.get("max_new_tokens", 32))
    max_prompt = int(args.get("max_prompt", 48))
    seed = int(args.get("seed", 0))
    top_k = int(args.get("top_k", 50))
    out_dir = args.get("out_dir")
    metrics_log = args.get("metrics_log")
    backend = args.get("backend", "inproc")
    kills = int(args.get("kills", 0))
    assert backend in ("inproc", "process"), backend
    assert kills == 0 or n_replicas >= 2, (
        "--kills needs >= 2 replicas (a surviving replica is what "
        "failover MTTR measures)")

    from flax import nnx

    from avenir_tpu.obs import JsonlSink, NullSink, reset_registry
    from avenir_tpu.serve import PRIORITIES, Router

    if out_dir:
        from avenir_tpu.checkpoint.io import load_checkpoint
        from avenir_tpu.sampling import model_from_checkpoint

        model, family = model_from_checkpoint(load_checkpoint(out_dir))
        print(f"serving {family} checkpoint from {out_dir}")
    else:
        from avenir_tpu.models.gpt import GPT, GPTConfig

        model = GPT(GPTConfig(
            block_size=int(args.get("block_size", 128)),
            vocab_size=int(args.get("vocab_size", 256)),
            n_layer=int(args.get("n_layer", 2)),
            n_head=int(args.get("n_head", 2)),
            n_embd=int(args.get("n_embd", 64)),
            dropout=0.0, bias=True, attn_impl="xla",
        ), rngs=nnx.Rngs(seed))
        print("serving a random-init tiny GPT (pass --out_dir for a ckpt)")

    cfg = model.config
    assert max_prompt + max_new <= cfg.block_size, (
        f"--max_prompt + --max_new_tokens must fit block_size "
        f"({max_prompt}+{max_new} > {cfg.block_size})"
    )

    reg = reset_registry()
    sink = NullSink()
    if metrics_log:
        os.makedirs(os.path.dirname(os.path.abspath(metrics_log)),
                    exist_ok=True)
        sink = JsonlSink(metrics_log)
    # --trace (ISSUE 10): per-request causal tracing + flight recorder.
    # The value is the Perfetto JSON output path (bare --trace uses
    # serve_trace.json); a sibling .events.jsonl feeds
    # tools/trace_report.py and flight-*.jsonl dumps land next to it.
    tracer = None
    trace_out = None
    trace_flag = args.get("trace")
    if trace_flag in ("0", "false"):  # the --prefix_sharing=0 convention
        trace_flag = None
    if trace_flag:
        from avenir_tpu.obs.trace import Tracer, set_tracer

        trace_out = (trace_flag if trace_flag not in ("1", "true")
                     else "serve_trace.json")
        flight_dir = os.path.dirname(os.path.abspath(trace_out))
        os.makedirs(flight_dir, exist_ok=True)
        tracer = Tracer(registry=reg, out_dir=flight_dir)
        set_tracer(tracer)  # phase spans + watchdog dumps see it too
    from avenir_tpu.obs.trace import install_crash_hooks, \
        disarm_crash_hooks

    # a crashed bench still leaves a final run_end snapshot (and a
    # flight dump when tracing) in the log — ISSUE 10 satellite
    install_crash_hooks(sink=sink, registry=reg, tracer=tracer)
    # speculative decoding (ISSUE 11): --spec_k arms spec_decode=draft
    # with a 1-layer random-init draft sharing the bench model's vocab
    # (pass --draft_layers/--draft_embd to reshape it). Checkpoint runs
    # would ship a real draft; the bench measures the machinery.
    draft_model = None
    if args.get("spec_k"):
        from avenir_tpu.models.gpt import GPT, GPTConfig

        assert not out_dir, (
            "--spec_k with --out_dir needs a draft checkpoint; the "
            "bench only builds random-init drafts for the tiny model")
        draft_model = GPT(GPTConfig(
            block_size=model.config.block_size,
            vocab_size=model.config.vocab_size,
            n_layer=int(args.get("draft_layers", 1)),
            n_head=2, n_embd=int(args.get("draft_embd", 32)),
            dropout=0.0, bias=True, attn_impl="xla",
        ), rngs=nnx.Rngs(seed + 7))
    # --anomaly (ISSUE 14): the fleet health engine rides the router —
    # every step feeds the series, the detector table checks at window
    # cadence, fires land in --metrics_log as `anomaly` records (and as
    # flight-anomaly-*.jsonl dumps when --trace arms a dump dir)
    ae = None
    if args.get("anomaly") not in (None, "0", "false"):
        from avenir_tpu.obs.anomaly import AnomalyEngine

        ae = AnomalyEngine(
            registry=reg, sink=sink, tracer=tracer,
            window_s=float(args.get("anomaly_window_s", 1.0)))
    router = Router(model, n_replicas=n_replicas, n_slots=n_slots,
                    registry=reg, sink=sink, seed=seed, backend=backend,
                    draft_model=draft_model, anomaly=ae,
                    engine_kwargs=_kv_engine_kwargs(args), tracer=tracer,
                    # the supervisor is the process backend's recovery
                    # story; inproc kills are revived below
                    supervise=(backend == "process" and kills > 0),
                    stall_floor_secs=float(args.get("stall_floor_secs",
                                                    10.0)))

    load_rng = np.random.default_rng(seed)
    # --load_shape (ISSUE 12 satellite): seeded non-homogeneous
    # arrival generators; the full shape config rides run_meta so the
    # bench replays bit-identically
    load_shape, load_kw = _load_cfg_from_args(args)
    arrivals, load_cfg = gen_arrivals(load_shape, load_rng, n_requests,
                                      rate, **load_kw)
    prompts = [
        [int(t) for t in load_rng.integers(0, cfg.vocab_size,
                                           int(load_rng.integers(2, max_prompt + 1)))]
        for _ in range(n_requests)
    ]
    priorities = ["batch" if load_rng.random() < batch_frac
                  else "interactive" for _ in range(n_requests)]

    # --autoscale=<max_replicas> (ISSUE 12 tentpole): arm the elastic
    # control plane — the fleet starts at --n_replicas and the
    # autoscaler grows/retires it against the SLO targets; decisions
    # land as `scale` trace events (arm --trace for the full audit
    # trail + fleet_report)
    scaler = None
    if args.get("autoscale"):
        from avenir_tpu.serve.autoscale import Autoscaler, SLOEngine

        slo = SLOEngine(
            slo_ttft_ms=slo_ttft_ms, slo_tpot_ms=slo_tpot_ms,
            target_attainment=float(args.get("min_attainment", 0.9)),
            window_s=float(args.get("slo_window_s", 10.0)),
            registry=reg)
        scaler = Autoscaler(
            router, slo,
            min_replicas=int(args.get("min_replicas", 1)),
            max_replicas=int(args.get("autoscale")),
            up_stable_s=float(args.get("up_stable_s", 1.0)),
            down_stable_s=float(args.get("down_stable_s", 6.0)),
            cooldown_s=float(args.get("cooldown_s", 3.0)),
            scale_to_zero=args.get("scale_to_zero") not in (None, "0",
                                                            "false"),
            prewarm=args.get("prewarm", "1") not in ("0", "false"),
            # a real-time serving loop must not freeze while a spawn
            # compiles: newcomers warm on a background thread
            spawn_async=True)

    sink.write({"kind": "run_meta", "t": time.time(), "model_type":
                type(model).__name__.lower(), "n_slots": n_slots,
                "n_replicas": n_replicas, "rate": rate,
                "n_requests": n_requests, "seed": seed,
                **load_cfg,
                **({"autoscale_max": scaler.max_replicas,
                    "autoscale_min": scaler.min_replicas}
                   if scaler is not None else {})})
    # kill schedule: evenly spaced completion milestones (the fleet is
    # warm and loaded when the axe falls, so MTTR measures failover,
    # not compile)
    kill_at = [(j + 1) * n_requests // (kills + 1) for j in range(kills)]
    kill_wall = []       # perf_counter stamp of each delivered kill
    submit_wall = {}     # rid -> perf_counter stamp at submit
    import random as _random

    kill_rng = _random.Random(seed)
    revive_due = {}      # inproc: replica_id -> step index to revive at
    t0 = time.perf_counter()
    submitted = 0
    step_n = 0
    done = []
    while len(done) < n_requests:
        now = time.perf_counter() - t0
        while submitted < n_requests and arrivals[submitted] <= now:
            rid = router.submit(prompts[submitted], max_new_tokens=max_new,
                                temperature=1.0, top_k=top_k,
                                priority=priorities[submitted])
            submit_wall[rid] = time.perf_counter()
            submitted += 1
        if len(kill_wall) < kills and len(done) >= kill_at[len(kill_wall)]:
            alive = [r for r in router.replicas if r.state != "dead"]
            # a meaningful MTTR needs a victim HOLDING work (an idle
            # kill has nothing to fail over) and a survivor to fail
            # over TO; otherwise defer to a later step. A retiring
            # replica is not a victim — the autoscaler is already
            # removing it, and the inproc revive below would race the
            # reaper for an id that no longer exists
            busy = [r for r in alive if r.busy
                    and r.replica_id not in router._retiring]
            if len(alive) >= 2 and busy:
                victim = kill_rng.choice(busy)
                if backend == "process":
                    import os as _os
                    import signal as _signal

                    _os.kill(victim.pid, _signal.SIGKILL)
                else:
                    router.kill_replica(victim.replica_id)
                    revive_due[victim.replica_id] = step_n + 30
                kill_wall.append(time.perf_counter())
                print(f"[serve_bench] killed replica {victim.replica_id} "
                      f"({backend}) after {len(done)} completions")
        for rid_, due in list(revive_due.items()):
            if step_n >= due:
                revive_due.pop(rid_)
                try:
                    router.revive_replica(rid_)
                except KeyError:
                    # the autoscaler reaped the corpse (dead retirees
                    # are removed, not revived) — nothing to bring back
                    pass
        if router.open_requests or router._pending:
            fins = router.step()
            done.extend(fins)
            step_n += 1
            if scaler is not None:
                scaler.observe(fins)
        elif submitted < n_requests:
            time.sleep(min(0.005, arrivals[submitted] - now))
        if scaler is not None:
            # poll every loop pass — idle passes included, so troughs
            # retire replicas and a scaled-to-zero fleet can wake
            scaler.poll()
    wall = time.perf_counter() - t0
    if tracer is not None:
        import json as _json

        from avenir_tpu.obs.trace import event_record, set_tracer

        # every trace event rides the metrics log as a `trace` record
        # (tools/trace_report.py reads either file)
        for ev in tracer.events():
            sink.write(event_record(ev))
        with open(trace_out, "w") as f:
            _json.dump(tracer.chrome(), f)
        events_out = trace_out.rsplit(".json", 1)[0] + ".events.jsonl"
        tracer.write_events_jsonl(events_out)
        set_tracer(None)
        print(f"trace: {trace_out} (load in Perfetto / chrome://tracing)"
              f"\ntrace events: {events_out} "
              f"(attribute: python tools/trace_report.py {events_out})")
    disarm_crash_hooks()  # the normal run_end below supersedes
    # ONE quantile rule (ISSUE 14): latency percentiles come from the
    # shared streaming sketch, and the run_end record carries the
    # sketch snapshots — obs_report prints its p50/p99 lines from the
    # artifact instead of re-deriving them from per-request records
    from avenir_tpu.obs.series import QuantileSketch

    ttft_sk, tpot_sk = QuantileSketch(), QuantileSketch()
    for f in done:
        if f.ttft_ms is not None:
            ttft_sk.observe(f.ttft_ms)
        if f.n_out > 1:
            tpot_sk.observe(f.tpot_ms)
    series = reg.series_snapshot()  # the anomaly engine's, when armed
    series.setdefault("ttft_ms", {"key": "ttft_ms"})["sketch"] = \
        ttft_sk.to_dict()
    series.setdefault("tpot_ms", {"key": "tpot_ms"})["sketch"] = \
        tpot_sk.to_dict()
    snap = reg.snapshot()
    sink.write({"kind": "run_end", "t": time.time(),
                "counters": snap["counters"],
                "series": series,
                # gauges carry the paged-KV pool pressure for the
                # obs_report paging line (points, not totals)
                "gauges": {k: v for k, v in snap["gauges"].items()
                           if v is not None}})
    sink.close()

    def _skq(sk, q):
        v = sk.quantile(q)
        return float("nan") if v is None else v

    counters = reg.snapshot()["counters"]
    tokens_out = counters["tokens_out"]
    print(f"requests: {n_requests} at {rate:.1f} req/s (seed {seed}), "
          f"{n_replicas} {backend} replica(s) x {n_slots} slots, "
          f"wall {wall:.2f}s")
    print(f"ttft: p50 {_skq(ttft_sk, 0.50):.1f} ms  "
          f"p99 {_skq(ttft_sk, 0.99):.1f} ms")
    print(f"tpot: p50 {_skq(tpot_sk, 0.50):.2f} ms  "
          f"p99 {_skq(tpot_sk, 0.99):.2f} ms")
    print(f"goodput: {tokens_out / wall:,.1f} tok/s out "
          f"({tokens_out:.0f} tokens), "
          f"{len(done) / wall:.2f} req/s completed")
    for cls in PRIORITIES:
        fs = [f for f in done if f.priority == cls]
        if not fs:
            continue
        att = slo_attainment(fs, slo_ttft_ms=slo_ttft_ms,
                             slo_tpot_ms=slo_tpot_ms)
        cls_ttft = [f.ttft_ms for f in fs if f.ttft_ms is not None]
        refused = sum(f.finish_reason in ("shed", "rejected", "timeout")
                      for f in fs)
        print(f"slo[{cls}]: attainment {att:6.1%} of {len(fs)} "
              f"(ttft<={slo_ttft_ms:.0f}ms & tpot<={slo_tpot_ms:.0f}ms)  "
              f"ttft p99 {_pct(cls_ttft, 0.99):.1f} ms"
              + (f"  shed/rejected/timeout: {refused}" if refused else ""))
    if kill_wall:
        # failover MTTR: kill -> first re-dispatched token. A failover
        # survivor's TTFT counts from ORIGINAL submission and ends at
        # its first token on the replica that finished it (the dead
        # attempt's tokens were discarded), so submit stamp + TTFT is
        # that re-dispatched first-token instant.
        first_tok = [(submit_wall[f.req_id] + f.ttft_ms / 1e3)
                     for f in done
                     if f.failovers > 0 and f.ttft_ms is not None
                     and f.req_id in submit_wall]
        mttrs = []
        for tk in kill_wall:
            after = [t - tk for t in first_tok if t > tk]
            mttrs.append(min(after) if after else None)
        shown = ["n/a" if m is None else f"{m * 1e3:.0f}" for m in mttrs]
        print(f"failover mttr (kill -> first re-dispatched token): "
              f"{', '.join(shown)} ms over {len(kill_wall)} kill(s)  "
              f"[failovers {counters.get('serve_failovers', 0.0):.0f}, "
              f"respawns {counters.get('replica_respawns', 0.0):.0f}]")
    if scaler is not None:
        rs = counters.get("fleet_replica_seconds", 0.0)
        print(f"autoscale: +{counters.get('scale_up', 0.0):.0f}"
              f"/-{counters.get('scale_down', 0.0):.0f} decisions  "
              f"fleet {router.fleet_size} at end  "
              f"replica-seconds {rs:.1f} "
              f"(mean fleet {rs / wall:.2f} over {wall:.1f}s)")
    if backend == "inproc":
        n_prefills = sum(len(r.engine.traces["prefill"])
                         for r in router.replicas)
        n_steps = sum(len(r.engine.traces["step"])
                      for r in router.replicas)
        print(f"compiles: {n_prefills} prefill bucket(s) "
              f"+ {n_steps} decode step(s) across {n_replicas} replica(s)")
    if metrics_log:
        print(f"metrics: {metrics_log} "
              f"(summarize: python tools/obs_report.py {metrics_log})")
    if scaler is not None:
        scaler.close()  # a still-warming spawn must not outlive the run
    router.close()


if __name__ == "__main__":
    main()
