"""Chaos harness for the serve fleet: kill and restart replicas under
seeded Poisson load and prove the router's promises hold (ISSUE 6).

The claims this drill checks are concrete (docs/SERVING.md):

  1. ZERO LOST: every accepted request reaches exactly ONE terminal
     state — served, or an explicit `timeout`/`shed` — no matter how
     many replicas die under it.
  2. BIT-IDENTICAL: every SERVED request's tokens match a one-shot
     `generate_cached(model, rng, prompt, ...)` run of the same
     (prompt, rng, sampling) — failover re-prefills from scratch, so
     surviving a replica kill never changes a single token.
  3. FAIR-SHARE: while batch traffic saturates the fleet, interactive
     p99 TTFT stays bounded (and well under batch p99).

Replica deaths come through the production paths of the chosen
backend (ISSUE 8):

  --backend=inproc (default): the `serve_step_fail` fault site (an
    engine step raising mid-decode), the `replica_stall` silent wedge,
    and abrupt `kill_replica` calls at seeded step indices (the
    SIGKILL analogue). Dead replicas are revived a fixed number of
    router steps later, like a supervisor restarting a pod.

  --backend=process: each replica is a REAL worker process, and the
    kills are real too — `os.kill(pid, SIGKILL)` mid-decode (>= 3 of
    them), an armed `worker_hang` wedge (caught by the RPC timeout),
    and an armed `frame_corrupt` CRC trip. Recovery is the
    RespawnSupervisor respawning dead workers with capped backoff —
    nothing in the drill revives anything by hand.

Emits a BENCH-style JSON report; exits non-zero if any assertion
fails, so CI can gate on it.

    python tools/chaos_serve.py --seed=0 --kills=3 --out=BENCH_chaos_serve.json
    python tools/chaos_serve.py --backend=process --seed=0 --kills=5 \
        --out=BENCH_chaos_proc.json
"""

import json
import os
import random
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from avenir_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()


def _parse_args():
    return {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}


def main():
    t_start = time.time()
    a = _parse_args()
    backend = a.get("backend", "inproc")
    assert backend in ("inproc", "process"), backend
    cfg = {
        "backend": backend,
        "seed": int(a.get("seed", 0)),
        "n_requests": int(a.get("n_requests", 60)),
        "n_replicas": int(a.get("n_replicas", 2)),
        "n_slots": int(a.get("n_slots", 2)),
        # process mode cycles sigkill/hang/sigkill/corrupt/sigkill, so
        # the default 5 delivers the >= 3 real SIGKILLs the drill's
        # acceptance asks for plus one of each fault
        "kills": int(a.get("kills", 5 if backend == "process" else 3)),
        "rate": float(a.get("rate", 200.0)),
        "max_new": int(a.get("max_new_tokens", 8)),
        "batch_frac": float(a.get("batch_frac", 0.7)),
        "deadline_frac": float(a.get("deadline_frac", 0.25)),
        "revive_after": int(a.get("revive_after", 15)),
        # process kills pay respawn (fresh jax import + compiles) and
        # hang detection (RPC timeout) windows inside TTFT tails
        "ttft_bound_ms": float(a.get(
            "ttft_bound_ms", 30_000.0 if backend == "process" else 2500.0)),
        "out": a.get("out", ""),
    }
    rng = random.Random(cfg["seed"])

    import jax
    import jax.numpy as jnp
    import numpy as np
    from flax import nnx

    from avenir_tpu.infer.decode import generate_cached
    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs import reset_registry
    from avenir_tpu.obs.report import percentile
    from avenir_tpu.serve import Router
    from avenir_tpu.utils.faults import FaultInjector, set_injector

    model = GPT(GPTConfig(block_size=64, vocab_size=256, n_layer=2,
                          n_head=2, n_embd=64, dropout=0.0, bias=True,
                          attn_impl="xla"), rngs=nnx.Rngs(cfg["seed"]))

    # -- deterministic request mix (one prompt bucket: len 3..8, so the
    # warmup below covers every prefill compile) + one-shot references
    load = np.random.default_rng(cfg["seed"])
    arrivals = np.cumsum(load.exponential(1.0 / cfg["rate"],
                                          cfg["n_requests"]))
    requests = []
    print(f"[chaos-serve] computing {cfg['n_requests']} one-shot "
          "reference streams")
    for i in range(cfg["n_requests"]):
        t0 = int(load.integers(3, 9))
        prompt = [int(t) for t in load.integers(0, 256, t0)]
        priority = "batch" if load.random() < cfg["batch_frac"] \
            else "interactive"
        deadline = (float(load.integers(100, 400))
                    if priority == "batch"
                    and load.random() < cfg["deadline_frac"] else None)
        key = jax.random.key(10_000 + i)
        ref = np.asarray(generate_cached(
            model, key, jnp.asarray(prompt, jnp.int32)[None],
            cfg["max_new"], temperature=1.0, top_k=32))[0]
        requests.append({"prompt": prompt, "priority": priority,
                         "deadline_ms": deadline, "rng": key,
                         "ref": [int(t) for t in ref]})

    reg = reset_registry()
    if backend == "process":
        from avenir_tpu.utils.retry import RetryPolicy

        router = Router(model, n_replicas=cfg["n_replicas"],
                        n_slots=cfg["n_slots"], max_seq_len=32,
                        registry=reg, seed=cfg["seed"],
                        stall_floor_secs=0.5, backend="process",
                        supervise=True,
                        respawn_policy=RetryPolicy(
                            attempts=8, base_s=0.25, cap_s=4.0,
                            jitter=0.25,
                            rng=random.Random(cfg["seed"])))
    else:
        router = Router(model, n_replicas=cfg["n_replicas"],
                        n_slots=cfg["n_slots"], max_seq_len=32,
                        registry=reg, seed=cfg["seed"],
                        stall_floor_secs=0.5)

    # warmup: one request per replica pays every compile (prefill bucket
    # + decode step) BEFORE the clock starts, so TTFT measures the
    # serving system, not XLA
    for r in range(cfg["n_replicas"]):
        router.submit([1 + r, 2, 3], max_new_tokens=2, top_k=32)
    router.drain()

    # seeded kill schedule: step index -> mode, cycling every death
    # path of the chosen backend so the drill proves every DETECTION
    # path. inproc: abrupt kill_replica (the SIGKILL analogue), the
    # serve_step_fail site (step exception mid-decode), the
    # replica_stall site (silent wedge, caught by the heartbeat
    # threshold). process: REAL os.kill SIGKILLs (pipe EOF), an armed
    # worker_hang (RPC timeout), an armed frame_corrupt (CRC trip).
    # process modes SIGKILL-first: late planned steps can fall past the
    # drain (kill steps only tick while work is open), and the >= 3
    # real kills are the acceptance bar — hang/corrupt ride behind
    modes = (("sigkill", "sigkill", "sigkill", "hang", "corrupt")
             if backend == "process" else ("kill", "fault", "stall"))
    span = (6 if backend == "process" else 12) * cfg["kills"]
    kill_steps = sorted(rng.sample(range(4, 4 + span), cfg["kills"]))
    kill_plan = {s: modes[i % len(modes)]
                 for i, s in enumerate(kill_steps)}
    prev_inj = set_injector(FaultInjector("", seed=cfg["seed"]))

    report = {"tool": "chaos_serve", "seed": cfg["seed"],
              "backend": backend,
              "config": {k: cfg[k] for k in
                         ("n_requests", "n_replicas", "n_slots", "kills",
                          "rate", "max_new", "batch_frac",
                          "deadline_frac", "revive_after",
                          "ttft_bound_ms")},
              "kills": [], "ok": True}
    done, submitted, step_n = [], 0, 0
    death_step = {}
    pending_kills = []  # planned kills deferred past all-dead windows
    t0 = time.perf_counter()
    try:
        while len(done) < cfg["n_requests"]:
            now = time.perf_counter() - t0
            while (submitted < cfg["n_requests"]
                   and arrivals[submitted] <= now):
                q = requests[submitted]
                rid = router.submit(
                    q["prompt"], max_new_tokens=cfg["max_new"],
                    temperature=1.0, top_k=32, rng=q["rng"],
                    deadline_ms=q["deadline_ms"], priority=q["priority"])
                q["rid"] = rid
                submitted += 1
            if router.open_requests or router._pending:
                step_n += 1
                if step_n in kill_plan:
                    # queue rather than fire-and-forget: a kill whose
                    # step lands in an all-dead window must still be
                    # DELIVERED once something is alive to kill, or the
                    # drill under-counts its own chaos
                    pending_kills.append(kill_plan[step_n])
                # deliver at most one pending kill per step; a kill is
                # popped and RECORDED only once it actually landed —
                # an arm RPC racing the victim's natural death, or a
                # corpse with no pid, re-tries next step (the report's
                # kills[] must only claim chaos that was delivered)
                alive = [r.replica_id for r in router.replicas
                         if r.state != "dead"]
                if pending_kills and len(alive) > 0:
                    mode = pending_kills[0]
                    delivered = False
                    victim = None
                    if mode == "kill":
                        # only the abrupt kill names a victim; the fault
                        # sites fire on whichever replica steps next, so
                        # attributing them to a sampled id would lie
                        victim = rng.choice(alive)
                        router.kill_replica(victim)
                        delivered = True
                    elif mode == "sigkill":
                        # the real thing: the worker process dies with
                        # no goodbye frame; the router learns from pipe
                        # EOF on its next RPC
                        victim = rng.choice(alive)
                        pid = router.replicas[victim].pid
                        if pid is not None:
                            os.kill(pid, signal.SIGKILL)
                            delivered = True
                    elif mode in ("hang", "corrupt"):
                        # arm a one-shot worker-side fault over RPC on a
                        # WARMED victim (a cold, just-respawned worker is
                        # still under the RPC compile grace, which would
                        # stretch hang detection past the soak's
                        # patience): the victim wedges (RPC timeout) or
                        # corrupts its next reply frame (CRC trip)
                        warmed = [i for i in alive if router.replicas[i]
                                  ._n_busy_steps >= 2]
                        site = ("worker_hang" if mode == "hang"
                                else "frame_corrupt")
                        if warmed:
                            victim = rng.choice(warmed)
                            try:
                                router.replicas[victim].arm_fault(
                                    f"{site}:n=1", seed=cfg["seed"])
                                delivered = True
                            except Exception as e:  # died under the arm
                                print(f"[chaos-serve] arm {site} on "
                                      f"replica {victim} failed ({e!r}); "
                                      "re-queuing")
                    else:
                        # arm a one-shot fault: the next consulting
                        # replica raises (fault) or silently wedges
                        # until the stall threshold declares it (stall)
                        site = ("serve_step_fail" if mode == "fault"
                                else "replica_stall")
                        set_injector(FaultInjector(
                            f"{site}:n=1", seed=cfg["seed"]))
                        delivered = True
                    if delivered:
                        pending_kills.pop(0)
                        report["kills"].append(
                            {"step": step_n, "mode": mode,
                             "replica": victim})
                        print(f"[chaos-serve] step {step_n}: {mode} "
                              f"(replica {victim}, "
                              f"{router.open_requests} open)")
                if backend == "inproc":
                    # hand-driven revives; the process backend's
                    # recovery is the RespawnSupervisor inside step()
                    for r in router.replicas:
                        if (r.state == "dead"
                                and r.replica_id not in death_step):
                            death_step[r.replica_id] = step_n
                        if (r.state == "dead" and step_n
                                >= death_step.get(r.replica_id, step_n)
                                + cfg["revive_after"]):
                            router.revive_replica(r.replica_id)
                            death_step.pop(r.replica_id, None)
                            print(f"[chaos-serve] step {step_n}: revived "
                                  f"replica {r.replica_id}")
                done.extend(router.step())
            elif submitted < cfg["n_requests"]:
                time.sleep(min(0.005, arrivals[submitted] - now))
            assert time.perf_counter() - t0 < (
                900 if backend == "process" else 300), "chaos soak wedged"
    finally:
        set_injector(prev_inj)
    wall = time.perf_counter() - t0

    # -- the three claims --
    by_rid = {}
    for f in done:
        assert f.req_id not in by_rid, f"request {f.req_id} finished twice"
        by_rid[f.req_id] = f
    lost = [q["rid"] for q in requests if q["rid"] not in by_rid]
    served = mism = 0
    reasons = {}
    for q in requests:
        f = by_rid.get(q["rid"])
        if f is None:
            continue
        reasons[f.finish_reason] = reasons.get(f.finish_reason, 0) + 1
        if f.finish_reason in ("stop", "length"):
            served += 1
            if f.tokens != q["ref"]:
                mism += 1
        else:
            assert f.finish_reason in ("timeout", "shed"), (
                f"inexplicit terminal state {f.finish_reason!r}")
    it = [f.ttft_ms for f in done
          if f.priority == "interactive" and f.ttft_ms is not None]
    bt = [f.ttft_ms for f in done
          if f.priority == "batch" and f.ttft_ms is not None]
    p99_i = percentile(it, 0.99)
    p99_b = percentile(bt, 0.99)
    p50_i = percentile(it, 0.5)
    p50_b = percentile(bt, 0.5)
    counters = reg.snapshot()["counters"]
    # fairness = interactive p99 BOUNDED under batch saturation, and the
    # MEDIAN interactive wait under the median batch wait. The median —
    # not the tail — carries the no-starvation comparison: a single
    # stall-detection window (stall_floor_secs of wedged replica) lands
    # on whichever requests it lands on and rightly shows up in a
    # 28-sample p99, but fair-share is about the steady state
    fairness_ok = (p99_i is not None and p99_i <= cfg["ttft_bound_ms"]
                   and (p50_b is None or p50_i <= p50_b))
    zero_lost = not lost
    bit_identical = mism == 0
    n_sigkills = sum(k["mode"] == "sigkill" for k in report["kills"])
    # the process drill's acceptance: the kills must be REAL — at least
    # 3 SIGKILLed worker processes survived via failover + respawn
    sigkills_ok = backend != "process" or n_sigkills >= 3
    report.update({
        "wall_s": round(wall, 2),
        "submitted": submitted,
        "terminal": len(by_rid),
        "lost": lost,
        "zero_lost": zero_lost,
        "served": served,
        "bit_identical": bit_identical,
        "mismatches": mism,
        "finish_reasons": reasons,
        "failovers": counters.get("serve_failovers", 0.0),
        "shed": counters.get("serve_shed", 0.0),
        "timeouts": counters.get("serve_timeouts", 0.0),
        "replica_deaths": sum(r.deaths for r in router.replicas),
        "real_sigkills": n_sigkills,
        "respawns": counters.get("replica_respawns", 0.0),
        "rpc_timeouts": counters.get("rpc_timeouts", 0.0),
        "frame_crc_errors": counters.get("frame_crc_errors", 0.0),
        "ttft_ms": {
            "interactive": {"p50": p50_i, "p99": p99_i, "n": len(it)},
            "batch": {"p50": p50_b, "p99": p99_b, "n": len(bt)},
        },
        "fairness_ok": fairness_ok,
    })
    report["ok"] = (zero_lost and bit_identical and fairness_ok
                    and sigkills_ok)
    print(f"[chaos-serve] backend={backend}: {submitted} submitted, "
          f"{served} served bit_identical={bit_identical}, "
          f"{len(by_rid) - served} explicit timeout/shed, "
          f"lost={len(lost)}, deaths={report['replica_deaths']}, "
          f"failovers={report['failovers']:.0f}, "
          f"real_sigkills={n_sigkills}, "
          f"respawns={report['respawns']:.0f}")
    print(f"[chaos-serve] ttft interactive p50/p99 "
          f"{p50_i if p50_i is not None else float('nan'):.1f}/"
          f"{p99_i if p99_i is not None else float('nan'):.1f} ms vs "
          f"batch {p50_b if p50_b is not None else float('nan'):.1f}/"
          f"{p99_b if p99_b is not None else float('nan'):.1f} ms "
          f"(p99 bound {cfg['ttft_bound_ms']:.0f} ms) "
          f"fairness_ok={fairness_ok}")
    line = json.dumps(report)
    print(line)
    if cfg["out"]:
        with open(cfg["out"], "w") as f:
            f.write(line + "\n")
    router.close()  # reap process-backend workers
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
