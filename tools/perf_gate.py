"""Perf-regression gate over the committed BENCH trajectory (ISSUE 14
tentpole, part 3).

The repo's whole perf story lives in committed BENCH_*.json artifacts
(70.3k -> 143.8k tok/s/chip, 3.2x paging, 2.75x disagg, 29.7%
autoscale savings) — but until now nothing MACHINE-compared them, so a
silent 15% regression in any PR shipped clean. This tool closes that:
`PERF_LEDGER.json` pins each bench's headline metric plus a noise band
(derived from the recorded run variance — window spreads, search
granularity — with the source named per entry), and the gate fails
NON-ZERO, naming the metric and the band, when an artifact falls below
the band. It also refuses any artifact whose own acceptance flag
(`ok`) went false — a bench that failed its bar must not ship quietly.

Modes:

    --check                 verify every ledger entry against the
                            committed artifact it names (the tier-1
                            smoke: tests/test_perf_gate.py runs this on
                            HEAD — pure JSON reads, no model runs)
    --candidate=F --bench=B verify ONE fresh/candidate artifact F
                            against ledger entry B (run this on a new
                            bench output before committing it)
    --update                rewrite ledger `value`s from the committed
                            artifacts (after an INTENDED perf change;
                            bands and sources are preserved)

Exit codes: 0 = within bands, 1 = regression (message names the
metric, the measured value, and the band floor), 2 = ledger/artifact
unreadable (a missing artifact is a failure, not a skip — deleting a
bench must not pass the gate).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER = os.path.join(REPO, "PERF_LEDGER.json")


def dig(obj, path):
    """Walk a JSON path (list of keys/ints) into an artifact."""
    for k in path:
        obj = obj[k]
    return float(obj)


def load_json(path):
    with open(path) as f:
        return json.load(f)


def check_entry(name, entry, value):
    """One ledger comparison. Returns (ok, message). `direction`
    'higher' means bigger is better: the floor is
    ledger_value * (1 - noise_frac); 'lower' mirrors it. A value
    BETTER than the ledger passes (with a refresh hint) — the gate
    guards regressions, it does not freeze improvements out."""
    ref = float(entry["value"])
    noise = float(entry["noise_frac"])
    if entry.get("direction", "higher") == "higher":
        floor = ref * (1.0 - noise)
        ok = value >= floor
        msg = (f"{name}: {value:g} vs ledger {ref:g} "
               f"(band -{noise:.1%} => floor {floor:g})")
    else:
        ceil = ref * (1.0 + noise)
        ok = value <= ceil
        msg = (f"{name}: {value:g} vs ledger {ref:g} "
               f"(band +{noise:.1%} => ceiling {ceil:g})")
    if not ok:
        msg = "REGRESSION " + msg
    elif (value > ref * (1.0 + noise)
          if entry.get("direction", "higher") == "higher"
          else value < ref * (1.0 - noise)):
        msg += "  [improved beyond the band — refresh with --update]"
    return ok, msg


def check_artifact(name, entry, artifact_path):
    try:
        art = load_json(artifact_path)
    except (OSError, json.JSONDecodeError) as e:
        return None, (f"{name}: cannot read {artifact_path} ({e}) — a "
                      "missing/torn bench artifact fails the gate")
    try:
        value = dig(art, entry["path"])
    except (KeyError, IndexError, TypeError, ValueError) as e:
        return None, (f"{name}: {artifact_path} has no "
                      f"{'.'.join(map(str, entry['path']))} ({e})")
    ok, msg = check_entry(name, entry, value)
    # the artifact's own acceptance flag: a bench that failed its bar
    # must fail the gate even if the headline metric looks fine
    if entry.get("require_ok", True) and "ok" in art \
            and art["ok"] is not True:
        ok = False
        msg += "  [artifact's own ok flag is false]"
    return ok, msg


def run_check(ledger, *, only=None, artifact_override=None):
    failures = 0
    hard_errors = 0
    for name, entry in sorted(ledger["benches"].items()):
        if only is not None and name != only:
            continue
        path = (artifact_override if artifact_override is not None
                else os.path.join(REPO, entry["artifact"]))
        ok, msg = check_artifact(name, entry, path)
        if ok is None:
            hard_errors += 1
            print(f"[perf_gate] ERROR {msg}")
        elif not ok:
            failures += 1
            print(f"[perf_gate] FAIL  {msg}")
        else:
            print(f"[perf_gate] ok    {msg}")
    if only is not None and not any(
            n == only for n in ledger["benches"]):
        print(f"[perf_gate] ERROR unknown bench {only!r} — ledger has "
              f"{sorted(ledger['benches'])}")
        return 2
    if hard_errors:
        return 2
    return 1 if failures else 0


def run_update(ledger, ledger_path=LEDGER):
    for name, entry in sorted(ledger["benches"].items()):
        path = os.path.join(REPO, entry["artifact"])
        art = load_json(path)
        new = dig(art, entry["path"])
        if new != entry["value"]:
            print(f"[perf_gate] {name}: {entry['value']:g} -> {new:g}")
            entry["value"] = new
    # write back to the ledger that was READ — an --update against a
    # --ledger override must not clobber the committed baseline
    with open(ledger_path, "w") as f:
        json.dump(ledger, f, indent=1)
        f.write("\n")
    print(f"[perf_gate] ledger rewritten: {ledger_path}")
    return 0


def main(argv):
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in argv}
    try:
        ledger = load_json(args.get("ledger", LEDGER))
    except (OSError, json.JSONDecodeError) as e:
        print(f"[perf_gate] ERROR cannot read ledger: {e}")
        return 2
    if "update" in args:
        return run_update(ledger, args.get("ledger", LEDGER))
    if "candidate" in args:
        bench = args.get("bench")
        if not bench:
            print("[perf_gate] --candidate needs --bench=<ledger name>")
            return 2
        return run_check(ledger, only=bench,
                         artifact_override=args["candidate"])
    if "check" in args:
        return run_check(ledger)
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
