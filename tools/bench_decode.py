"""Cached-decode latency/throughput on the real chip (VERDICT r2 item 6 /
r1 item 9 remainder), plus the decode-raw-speed knob grid (ISSUE 11):
speculative decoding and int8 KV measured through the serve engine.

Part 1 — one-shot decode latency (`generate_cached`): ONE fused dispatch
(nnx.scan over tokens). Per-token latency is isolated from prefill and
dispatch overhead by timing two compiled runs — N tokens and 1 token —
and dividing the DELTA by N-1 (both runs pay the same prefill +
round-trip; the difference is N-1 decode-scan iterations).

Part 2 — the engine knob grid (`--engine`): drives `serve.Engine` on the
tiny-GPT bench (an 8-layer random-init target with a 1-layer draft,
shared vocab) across spec_decode={off,draft} x spec_k x kv_dtype.
Decode tokens/s comes from the engine's own `serve_decode_ms` span
counter (prefill excluded by construction); accept rate from the
`spec_accepted`/`spec_proposed` counters; and the headline **effective
tokens per model pass** = tokens_out / per-slot verify passes — the
number that makes BENCH artifacts comparable across this knob grid
(a 0.7 accept rate at k=4 is ~2.9 tokens per pass; sequential is 1.0
by definition).

Usage:
    python tools/bench_decode.py [--tokens=N] [--batch=N]    # part 1
    python tools/bench_decode.py --engine [--spec_ks=4,8]
        [--kv_dtype=bf16|int8] [--max_new=N] [--json=PATH]   # part 2
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
from flax import nnx


def _timed_run(model, rng, idx, n_tokens):
    from avenir_tpu.infer.decode import generate_cached

    t0 = time.perf_counter()
    out = generate_cached(model, rng, idx, n_tokens, temperature=1.0,
                          top_k=50)
    np.asarray(out[0, -1:])  # fence
    return time.perf_counter() - t0


def bench_one(name, model, *, batch, prompt_len, new_tokens):
    from avenir_tpu.infer.decode import generate_cached

    rng = jax.random.key(0)
    idx = jax.numpy.asarray(
        np.random.default_rng(0).integers(0, 1000, (batch, prompt_len))
        .astype(np.int32))
    for n in (1, new_tokens):  # compile both scan lengths
        out = generate_cached(model, rng, idx, n, temperature=1.0, top_k=50)
        np.asarray(out[0, -1:])
    t1 = _timed_run(model, rng, idx, 1)
    tN = _timed_run(model, rng, idx, new_tokens)
    per_tok_ms = (tN - t1) / (new_tokens - 1) * 1e3
    print(f"{name}: batch={batch} prompt={prompt_len} new={new_tokens} "
          f"-> {per_tok_ms:.2f} ms/token decode-only "
          f"({batch * (new_tokens - 1) / (tN - t1):,.0f} tok/s aggregate); "
          f"prefill+1tok+RTT overhead {t1*1e3:.1f} ms")
    return {"name": name, "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, "per_tok_ms": per_tok_ms}


# ---------------------------------------------------------------------------
# Part 2: the serve-engine knob grid (spec decoding + int8 KV)
# ---------------------------------------------------------------------------


def bench_engine_cell(model, draft, *, spec_k, kv_dtype, kv_impl,
                      prompts, max_new, n_slots, max_seq_len, seed):
    """One grid cell: build an engine with the knobs, warm every
    compile, then measure a seeded closed batch. Decode tok/s =
    tokens_out / serve_decode_ms — prefill is excluded by the span
    split, so the number is the decode path alone (what spec + int8
    actually move)."""
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Engine

    kw = {}
    if spec_k:
        kw = dict(spec_decode="draft", spec_k=spec_k, draft_model=draft)
    eng = Engine(model, n_slots=n_slots, max_seq_len=max_seq_len,
                 registry=MetricsRegistry(), kv_dtype=kv_dtype,
                 kv_impl=kv_impl, **kw)
    # warmup: every prefill bucket + the decode/spec step compile here
    for p in prompts:
        eng.submit(list(p), max_new_tokens=max_new, temperature=1.0)
    eng.drain()
    reg = MetricsRegistry()
    eng._reg = reg
    eng._tick_n = 0
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        eng.submit(list(p), max_new_tokens=max_new, temperature=1.0,
                   rng=jax.random.key(seed * 1000 + i))
    done = eng.drain()
    wall = time.perf_counter() - t0
    assert all(f.finish_reason == "length" for f in done)
    c = reg.snapshot()["counters"]
    toks = c["tokens_out"]
    decode_s = c["serve_decode_ms"] / 1e3
    proposed = c.get("spec_proposed", 0.0)
    accepted = c.get("spec_accepted", 0.0)
    accept_rate = accepted / proposed if proposed else None
    # per-slot verify passes: spec_proposed counts spec_k per live slot
    # per tick, so proposed/spec_k IS the slot-tick count; sequential
    # emits exactly one token per slot-tick
    slot_ticks = proposed / spec_k if spec_k else toks
    eff_tokens_per_pass = toks / slot_ticks if slot_ticks else 1.0
    row = {
        "spec_decode": "draft" if spec_k else "off",
        "spec_k": spec_k or None,
        "kv_dtype": kv_dtype,
        "kv_impl": kv_impl,
        "tokens_out": toks,
        "decode_ms": c["serve_decode_ms"],
        "decode_tok_per_s": toks / decode_s if decode_s else None,
        "wall_s": wall,
        "accept_rate": accept_rate,
        "eff_tokens_per_pass": eff_tokens_per_pass,
        "verify_ticks": eng._tick_n,
    }
    print(f"[engine] spec={'off' if not spec_k else f'k={spec_k}'}"
          f" kv_dtype={kv_dtype} kv_impl={kv_impl}: "
          f"{row['decode_tok_per_s']:,.0f} decode tok/s"
          + (f"  accept {accept_rate:.2f}  "
             f"{eff_tokens_per_pass:.2f} tok/pass" if spec_k else
             "  1.00 tok/pass"))
    return row


def engine_grid(args):
    """The ISSUE 11 tiny-GPT bench: spec off vs spec_k grid (x kv_dtype)
    through the serve engine, JSON-able for BENCH artifacts."""
    from avenir_tpu.models.gpt import GPT, GPTConfig

    seed = int(args.get("seed", 0))
    vocab = int(args.get("vocab_size", 256))
    max_new = int(args.get("max_new", 48))
    n_slots = int(args.get("n_slots", 8))
    max_seq_len = int(args.get("max_seq_len", 128))
    kv_impl = args.get("kv_impl", "slab")
    spec_ks = [int(k) for k in args.get("spec_ks", "4,8").split(",") if k]
    kv_dtypes = args.get("kv_dtypes", args.get("kv_dtype", "bf16")).split(",")
    # the tiny-GPT bench pair: an 8-layer target, a 1-layer narrow
    # draft — random-init, so the measured accept rate is the near-flat
    # distribution overlap (~0.7 at temperature 1.0), reported honestly
    # in the artifact rather than assumed
    tcfg = GPTConfig(
        block_size=256, vocab_size=vocab,
        n_layer=int(args.get("n_layer", 8)), n_head=4,
        n_embd=int(args.get("n_embd", 128)),
        dropout=0.0, bias=True, attn_impl="xla")
    dcfg = GPTConfig(
        block_size=256, vocab_size=vocab,
        n_layer=int(args.get("draft_layers", 1)), n_head=4,
        n_embd=int(args.get("draft_embd", 64)),
        dropout=0.0, bias=True, attn_impl="xla")
    model = GPT(tcfg, rngs=nnx.Rngs(seed))
    draft = GPT(dcfg, rngs=nnx.Rngs(seed + 7))
    rng = np.random.default_rng(seed)
    prompts = [[int(t) for t in rng.integers(0, vocab, 32)]
               for _ in range(n_slots)]

    rows = []
    for kv_dtype in kv_dtypes:
        for spec_k in [0] + spec_ks:
            rows.append(bench_engine_cell(
                model, draft, spec_k=spec_k, kv_dtype=kv_dtype,
                kv_impl=kv_impl, prompts=prompts, max_new=max_new,
                n_slots=n_slots, max_seq_len=max_seq_len, seed=seed))
    base = {r["kv_dtype"]: r["decode_tok_per_s"] for r in rows
            if r["spec_decode"] == "off"}
    for r in rows:
        r["speedup_vs_off"] = (r["decode_tok_per_s"] / base[r["kv_dtype"]]
                               if base.get(r["kv_dtype"]) else None)
    best = max((r for r in rows if r["spec_k"]),
               key=lambda r: r["speedup_vs_off"] or 0.0, default=None)
    bench = {
        "kind": "decode_bench",
        "config": {
            "seed": seed, "vocab_size": vocab, "max_new": max_new,
            "n_slots": n_slots, "max_seq_len": max_seq_len,
            "target": {"n_layer": tcfg.n_layer, "n_embd": tcfg.n_embd},
            "draft": {"n_layer": dcfg.n_layer, "n_embd": dcfg.n_embd},
            "temperature": 1.0,
        },
        "rows": rows,
        "extra": {
            "kv_dtype": ",".join(kv_dtypes),
            "spec_k": spec_ks,
            "accept_rate": {f"k={r['spec_k']}": r["accept_rate"]
                            for r in rows if r["spec_k"]},
            "eff_tokens_per_pass": {
                f"k={r['spec_k']}" if r["spec_k"] else "off":
                    r["eff_tokens_per_pass"] for r in rows},
            "best_speedup_vs_off": (best or {}).get("speedup_vs_off"),
        },
    }
    for r in rows:
        if r["spec_k"]:
            print(f"  -> spec_k={r['spec_k']} kv_dtype={r['kv_dtype']}: "
                  f"{r['speedup_vs_off']:.2f}x decode tok/s vs off, "
                  f"{r['eff_tokens_per_pass']:.2f} effective "
                  "tokens/model-pass")
    out = args.get("json")
    if out:
        with open(out, "w") as f:
            json.dump(bench, f, indent=1)
        print(f"[engine] wrote {out}")
    return bench


# ---------------------------------------------------------------------------
# Part 3: the composition bench (ISSUE 18) — spec × sharing × disagg,
# the n-gram self-draft, adaptive k
# ---------------------------------------------------------------------------


def _tiny_pair(args, seed):
    """The part-2 tiny-GPT bench pair, re-initialized per seed."""
    from avenir_tpu.models.gpt import GPT, GPTConfig

    vocab = int(args.get("vocab_size", 256))
    tcfg = GPTConfig(block_size=256, vocab_size=vocab,
                     n_layer=int(args.get("n_layer", 8)), n_head=4,
                     n_embd=int(args.get("n_embd", 128)),
                     dropout=0.0, bias=True, attn_impl="xla")
    dcfg = GPTConfig(block_size=256, vocab_size=vocab,
                     n_layer=int(args.get("draft_layers", 1)), n_head=4,
                     n_embd=int(args.get("draft_embd", 64)),
                     dropout=0.0, bias=True, attn_impl="xla")
    return GPT(tcfg, rngs=nnx.Rngs(seed)), GPT(dcfg, rngs=nnx.Rngs(seed + 7))


def _timed_pass(submit_all, drain, reg):
    """Two warm passes + timed pass; decode tok/s comes from the
    COUNTER DELTAS across the timed pass (registries are
    engine-lifetime, so deltas measure the pass, not the warmup). Two
    warm waves, not one: the adaptive-k controller walks the bucket
    ladder as its EWMA settles, and every rung it will visit at steady
    state must be traced BEFORE the timed wave."""
    submit_all(0)
    drain()
    submit_all(1)
    drain()
    # two timed waves, BEST tok/s wins: background load on a shared
    # host only ever slows a wave down, so max-over-waves is the
    # noise-robust estimator (same argument as min-of-N wall times)
    waves = []
    snaps = [dict(reg.snapshot()["counters"])]
    for w in (2, 3):
        submit_all(w)
        drain()
        snaps.append(dict(reg.snapshot()["counters"]))
        c0, c1 = snaps[-2], snaps[-1]
        toks = c1.get("tokens_out", 0.0) - c0.get("tokens_out", 0.0)
        ms = (c1.get("serve_decode_ms", 0.0)
              - c0.get("serve_decode_ms", 0.0))
        waves.append({"tokens_out": toks, "decode_ms": ms,
                      "decode_tok_per_s": toks / (ms / 1e3) if ms
                      else None})
    best = max(waves, key=lambda r: r["decode_tok_per_s"] or 0.0)
    c0, c1 = snaps[0], snaps[-1]

    def delta(key):
        return c1.get(key, 0.0) - c0.get(key, 0.0)

    proposed, accepted = delta("spec_proposed"), delta("spec_accepted")
    return {
        "tokens_out": best["tokens_out"],
        "decode_ms": best["decode_ms"],
        "decode_tok_per_s": best["decode_tok_per_s"],
        "wave_tok_per_s": [r["decode_tok_per_s"] for r in waves],
        "accept_rate": accepted / proposed if proposed else None,
        "ngram_hits": delta("ngram_hits") or None,
        "spec_k_effective": reg.snapshot()["gauges"].get(
            "spec_k_effective"),
    }


def _router_compose_cell(model, draft, *, spec, seed, prompts, max_new,
                         n_slots, max_seq_len):
    """One compose cell: a 2-replica disagg fleet (1 prefill-class, 1
    decode-class) with paged KV + prefix sharing on — spec (model
    draft, k=4) on the decode class vs spec off, same topology, same
    seeded workload. Decode tok/s is the decode replica's own
    serve_decode_ms span (prefill and transfer excluded)."""
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Router

    reg = MetricsRegistry()
    ekw = dict(kv_impl="paged", page_size=16, prefill_chunk=32)
    kw = {}
    if spec:
        ekw.update(spec_decode="draft", spec_k=4)
        kw["draft_model"] = draft
    router = Router(model, n_replicas=2, n_slots=n_slots,
                    max_seq_len=max_seq_len, registry=reg, seed=0,
                    n_prefill=1, engine_kwargs=ekw, **kw)

    def submit_all(wave):
        for i, p in enumerate(prompts):
            router.submit(list(p), max_new_tokens=max_new,
                          temperature=1.0,
                          rng=jax.random.key(seed * 10000 + wave * 100
                                             + i))

    row = _timed_pass(submit_all, router.drain, reg)
    router.close()
    return row


def _engine_cell2(model, *, draft=None, spec_k=0, prompts, max_new,
                  n_slots, max_seq_len, seed, top_k):
    """Engine-level cell for the ngram / adaptive-k grids (slab KV;
    spec_k may be an int, 'auto', or 0 = off; draft may be a model or
    'ngram')."""
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Engine

    reg = MetricsRegistry()
    kw = {}
    if spec_k:
        kw = dict(spec_decode="draft", spec_k=spec_k, draft_model=draft)
    eng = Engine(model, n_slots=n_slots, max_seq_len=max_seq_len,
                 registry=reg, **kw)

    def submit_all(wave):
        for i, p in enumerate(prompts):
            eng.submit(list(p), max_new_tokens=max_new, temperature=1.0,
                       top_k=top_k,
                       rng=jax.random.key(seed * 10000 + wave * 100 + i))

    return _timed_pass(submit_all, eng.drain, reg)


def spec_compose_bench(args):
    """ISSUE 18 acceptance bench, three cells x three seeds:

    - compose: disagg fleet (sharing + paged + handoff ON), model-draft
      spec vs off — the >= 1.5x decode tok/s headline;
    - ngram: the draft-free self-draft on a LOOKUP workload (repetitive
      prompts, greedy) vs spec off — the > 1.3x headline. Greedy is the
      honest cell: at temperature 1.0 a point-mass proposal accepts
      with ~1/V probability, so sampled ngram would only measure noise;
    - adaptive_k: spec_k='auto' vs off (reported, ungated — the knob
      buys robustness, its steady-state speed rides the same ladder).

    The compose and adaptive cells run at n_slots=2 — the LOW-BATCH
    latency-bound regime speculative decoding exists for. At high
    batch the (k+1)-wide verify goes flop-bound and spec loses money
    (measured: 0.77x at batch 16 on this host); that is precisely the
    accept-collapse regime docs/OPERATIONS.md tells operators to run
    spec_k='auto' in, so the headline is pinned to the regime where an
    operator would actually turn the knob on. The ngram cell keeps
    batch 8: a point-mass proposal verifies at the same width but
    skips the draft dispatches, so it stays ahead even batched.

    Headlines are the MEDIAN seed's speedup; the seed spread feeds the
    PERF_LEDGER noise band."""
    seeds = [int(s) for s in args.get("seeds", "0,1,2").split(",") if s]
    max_new = int(args.get("max_new", 48))
    n_slots = int(args.get("n_slots", 8))
    lat_slots = int(args.get("lat_slots", 2))
    lat_reqs = int(args.get("lat_reqs", 6))
    max_seq_len = int(args.get("max_seq_len", 160))
    vocab = int(args.get("vocab_size", 256))
    cells = {"compose": [], "ngram": [], "adaptive_k": []}
    for seed in seeds:
        model, draft = _tiny_pair(args, seed)
        rng = np.random.default_rng(seed)
        # disagg workload: every prompt clears disagg_min_prompt (=32,
        # the prefill_chunk) so prefill happens on the prefill class
        # and EVERY decoded token rides a spliced chain; a 33-token
        # shared prefix makes sharing real work, not a no-op flag
        prefix = [int(t) for t in rng.integers(0, vocab, 33)]
        long_prompts = [prefix + [int(t) for t in rng.integers(0, vocab, 15)]
                        for _ in range(lat_reqs)]
        off = _router_compose_cell(
            model, None, spec=False, seed=seed, prompts=long_prompts,
            max_new=max_new, n_slots=lat_slots, max_seq_len=max_seq_len)
        on = _router_compose_cell(
            model, draft, spec=True, seed=seed, prompts=long_prompts,
            max_new=max_new, n_slots=lat_slots, max_seq_len=max_seq_len)
        cells["compose"].append({
            "seed": seed, "off": off, "spec": on,
            "speedup_vs_off": (on["decode_tok_per_s"]
                               / off["decode_tok_per_s"])})
        # lookup workload: repetitive prompts, greedy decode — the
        # regime prompt-lookup decoding exists for
        pat = [int(t) for t in rng.integers(0, vocab, 4)]
        look_prompts = [pat * 6 + [int(rng.integers(0, vocab))]
                        for _ in range(n_slots)]
        off = _engine_cell2(model, prompts=look_prompts, max_new=max_new,
                            n_slots=n_slots, max_seq_len=max_seq_len,
                            seed=seed, top_k=1)
        on = _engine_cell2(model, draft="ngram", spec_k=4,
                           prompts=look_prompts, max_new=max_new,
                           n_slots=n_slots, max_seq_len=max_seq_len,
                           seed=seed, top_k=1)
        cells["ngram"].append({
            "seed": seed, "off": off, "ngram": on,
            "speedup_vs_off": (on["decode_tok_per_s"]
                               / off["decode_tok_per_s"])})
        # adaptive k at temperature-1.0 sampling on plain prompts,
        # same low-batch regime as the compose cell
        rand_prompts = [[int(t) for t in rng.integers(0, vocab, 32)]
                        for _ in range(lat_reqs)]
        off = _engine_cell2(model, prompts=rand_prompts, max_new=max_new,
                            n_slots=lat_slots, max_seq_len=max_seq_len,
                            seed=seed, top_k=None)
        on = _engine_cell2(model, draft=draft, spec_k="auto",
                           prompts=rand_prompts, max_new=max_new,
                           n_slots=lat_slots, max_seq_len=max_seq_len,
                           seed=seed, top_k=None)
        cells["adaptive_k"].append({
            "seed": seed, "off": off, "auto": on,
            "spec_k_effective": on["spec_k_effective"],
            "speedup_vs_off": (on["decode_tok_per_s"]
                               / off["decode_tok_per_s"])})
        for name in cells:
            row = cells[name][-1]
            on_row = row.get("spec") or row.get("ngram") or row["auto"]
            acc = on_row["accept_rate"]
            print(f"[compose] seed={seed} {name}: "
                  f"{row['speedup_vs_off']:.2f}x"
                  f" (accept={acc if acc is None else round(acc, 2)},"
                  f" on_ms={on_row['decode_ms']:.0f},"
                  f" off_ms={row['off']['decode_ms']:.0f})")

    def headline(rows):
        sp = sorted(r["speedup_vs_off"] for r in rows)
        return sp[len(sp) // 2], (sp[-1] - sp[0]) / sp[len(sp) // 2]

    comp_med, comp_spread = headline(cells["compose"])
    ng_med, ng_spread = headline(cells["ngram"])
    auto_med, _ = headline(cells["adaptive_k"])
    ok = comp_med >= 1.5 and ng_med > 1.3
    bench = {
        "kind": "spec_compose_bench",
        "ok": ok,
        "config": {
            "seeds": seeds, "vocab_size": vocab, "max_new": max_new,
            "n_slots": n_slots, "lat_slots": lat_slots,
            "lat_reqs": lat_reqs, "max_seq_len": max_seq_len,
            "spec_k": 4, "temperature": 1.0,
            "compose_fleet": {"n_replicas": 2, "n_prefill": 1,
                              "kv_impl": "paged", "page_size": 16,
                              "prefill_chunk": 32,
                              "prefix_sharing": True},
            "ngram_workload": "4-token pattern x6 + 1 random, top_k=1",
        },
        "compose": {"speedup_vs_off": comp_med,
                    "seed_spread_frac": comp_spread,
                    "seeds": cells["compose"]},
        "ngram": {"speedup_vs_off": ng_med,
                  "seed_spread_frac": ng_spread,
                  "seeds": cells["ngram"]},
        "adaptive_k": {"speedup_vs_off": auto_med,
                       "seeds": cells["adaptive_k"]},
    }
    print(f"[compose] HEADLINES: compose {comp_med:.2f}x "
          f"(floor 1.5, ok={comp_med >= 1.5}), ngram {ng_med:.2f}x "
          f"(floor 1.3, ok={ng_med > 1.3}), adaptive-k {auto_med:.2f}x")
    out = args.get("json")
    if out:
        with open(out, "w") as f:
            json.dump(bench, f, indent=1)
            f.write("\n")
        print(f"[compose] wrote {out}")
    return bench


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    if "spec_compose" in args:
        spec_compose_bench(args)
        return
    if "engine" in args:
        engine_grid(args)
        return
    new_tokens = int(args.get("tokens", 128))
    assert new_tokens >= 2, "--tokens must be >= 2 (delta timing needs two lengths)"
    batch = int(args.get("batch", 1))

    from avenir_tpu.models.gpt import GPT, GPTConfig

    on_tpu = jax.default_backend() == "tpu"
    cdtype = "bfloat16" if on_tpu else "float32"
    gpt = GPT(GPTConfig(block_size=1024, vocab_size=50304, n_layer=12,
                        n_head=12, n_embd=768, dropout=0.0, bias=True,
                        compute_dtype=cdtype, attn_impl="xla"),
              rngs=nnx.Rngs(0))
    bench_one("gpt2-124m decode", gpt, batch=batch, prompt_len=128,
              new_tokens=new_tokens)

    from avenir_tpu.models.llama import Llama, LlamaConfig

    llama = Llama(LlamaConfig(block_size=4096, vocab_size=16384, n_layer=2,
                              n_head=32, n_kv_head=8, n_embd=4096,
                              ffn_hidden=14336, rope_theta=500000.0,
                              compute_dtype=cdtype, attn_impl="xla"),
                  rngs=nnx.Rngs(0))
    bench_one("llama8b-shape (L=2) decode", llama, batch=batch,
              prompt_len=128, new_tokens=new_tokens)


if __name__ == "__main__":
    main()
