"""Cached-decode latency/throughput on the real chip (VERDICT r2 item 6 /
r1 item 9 remainder: the KV-cache path had only ever run on the CPU test
harness).

The decode loop (infer/decode.py) is ONE fused dispatch (nnx.scan over
tokens). Per-token latency is isolated from prefill and dispatch overhead
by timing two compiled runs — N tokens and 1 token — and dividing the
DELTA by N-1 (both runs pay the same prefill + round-trip; the difference
is N-1 decode-scan iterations). Warmups compile both scan lengths first.

Usage: python tools/bench_decode.py [--tokens=N] [--batch=N]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
from flax import nnx


def _timed_run(model, rng, idx, n_tokens):
    from avenir_tpu.infer.decode import generate_cached

    t0 = time.perf_counter()
    out = generate_cached(model, rng, idx, n_tokens, temperature=1.0,
                          top_k=50)
    np.asarray(out[0, -1:])  # fence
    return time.perf_counter() - t0


def bench_one(name, model, *, batch, prompt_len, new_tokens):
    from avenir_tpu.infer.decode import generate_cached

    rng = jax.random.key(0)
    idx = jax.numpy.asarray(
        np.random.default_rng(0).integers(0, 1000, (batch, prompt_len))
        .astype(np.int32))
    for n in (1, new_tokens):  # compile both scan lengths
        out = generate_cached(model, rng, idx, n, temperature=1.0, top_k=50)
        np.asarray(out[0, -1:])
    t1 = _timed_run(model, rng, idx, 1)
    tN = _timed_run(model, rng, idx, new_tokens)
    per_tok_ms = (tN - t1) / (new_tokens - 1) * 1e3
    print(f"{name}: batch={batch} prompt={prompt_len} new={new_tokens} "
          f"-> {per_tok_ms:.2f} ms/token decode-only "
          f"({batch * (new_tokens - 1) / (tN - t1):,.0f} tok/s aggregate); "
          f"prefill+1tok+RTT overhead {t1*1e3:.1f} ms")


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    new_tokens = int(args.get("tokens", 128))
    assert new_tokens >= 2, "--tokens must be >= 2 (delta timing needs two lengths)"
    batch = int(args.get("batch", 1))

    from avenir_tpu.models.gpt import GPT, GPTConfig

    on_tpu = jax.default_backend() == "tpu"
    cdtype = "bfloat16" if on_tpu else "float32"
    gpt = GPT(GPTConfig(block_size=1024, vocab_size=50304, n_layer=12,
                        n_head=12, n_embd=768, dropout=0.0, bias=True,
                        compute_dtype=cdtype, attn_impl="xla"),
              rngs=nnx.Rngs(0))
    bench_one("gpt2-124m decode", gpt, batch=batch, prompt_len=128,
              new_tokens=new_tokens)

    from avenir_tpu.models.llama import Llama, LlamaConfig

    llama = Llama(LlamaConfig(block_size=4096, vocab_size=16384, n_layer=2,
                              n_head=32, n_kv_head=8, n_embd=4096,
                              ffn_hidden=14336, rope_theta=500000.0,
                              compute_dtype=cdtype, attn_impl="xla"),
                  rngs=nnx.Rngs(0))
    bench_one("llama8b-shape (L=2) decode", llama, batch=batch,
              prompt_len=128, new_tokens=new_tokens)


if __name__ == "__main__":
    main()
