"""Cached-decode latency/throughput on the real chip (VERDICT r2 item 6 /
r1 item 9 remainder), plus the decode-raw-speed knob grid (ISSUE 11):
speculative decoding and int8 KV measured through the serve engine.

Part 1 — one-shot decode latency (`generate_cached`): ONE fused dispatch
(nnx.scan over tokens). Per-token latency is isolated from prefill and
dispatch overhead by timing two compiled runs — N tokens and 1 token —
and dividing the DELTA by N-1 (both runs pay the same prefill +
round-trip; the difference is N-1 decode-scan iterations).

Part 2 — the engine knob grid (`--engine`): drives `serve.Engine` on the
tiny-GPT bench (an 8-layer random-init target with a 1-layer draft,
shared vocab) across spec_decode={off,draft} x spec_k x kv_dtype.
Decode tokens/s comes from the engine's own `serve_decode_ms` span
counter (prefill excluded by construction); accept rate from the
`spec_accepted`/`spec_proposed` counters; and the headline **effective
tokens per model pass** = tokens_out / per-slot verify passes — the
number that makes BENCH artifacts comparable across this knob grid
(a 0.7 accept rate at k=4 is ~2.9 tokens per pass; sequential is 1.0
by definition).

Usage:
    python tools/bench_decode.py [--tokens=N] [--batch=N]    # part 1
    python tools/bench_decode.py --engine [--spec_ks=4,8]
        [--kv_dtype=bf16|int8] [--max_new=N] [--json=PATH]   # part 2
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
from flax import nnx


def _timed_run(model, rng, idx, n_tokens):
    from avenir_tpu.infer.decode import generate_cached

    t0 = time.perf_counter()
    out = generate_cached(model, rng, idx, n_tokens, temperature=1.0,
                          top_k=50)
    np.asarray(out[0, -1:])  # fence
    return time.perf_counter() - t0


def bench_one(name, model, *, batch, prompt_len, new_tokens):
    from avenir_tpu.infer.decode import generate_cached

    rng = jax.random.key(0)
    idx = jax.numpy.asarray(
        np.random.default_rng(0).integers(0, 1000, (batch, prompt_len))
        .astype(np.int32))
    for n in (1, new_tokens):  # compile both scan lengths
        out = generate_cached(model, rng, idx, n, temperature=1.0, top_k=50)
        np.asarray(out[0, -1:])
    t1 = _timed_run(model, rng, idx, 1)
    tN = _timed_run(model, rng, idx, new_tokens)
    per_tok_ms = (tN - t1) / (new_tokens - 1) * 1e3
    print(f"{name}: batch={batch} prompt={prompt_len} new={new_tokens} "
          f"-> {per_tok_ms:.2f} ms/token decode-only "
          f"({batch * (new_tokens - 1) / (tN - t1):,.0f} tok/s aggregate); "
          f"prefill+1tok+RTT overhead {t1*1e3:.1f} ms")
    return {"name": name, "batch": batch, "prompt_len": prompt_len,
            "new_tokens": new_tokens, "per_tok_ms": per_tok_ms}


# ---------------------------------------------------------------------------
# Part 2: the serve-engine knob grid (spec decoding + int8 KV)
# ---------------------------------------------------------------------------


def bench_engine_cell(model, draft, *, spec_k, kv_dtype, kv_impl,
                      prompts, max_new, n_slots, max_seq_len, seed):
    """One grid cell: build an engine with the knobs, warm every
    compile, then measure a seeded closed batch. Decode tok/s =
    tokens_out / serve_decode_ms — prefill is excluded by the span
    split, so the number is the decode path alone (what spec + int8
    actually move)."""
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve import Engine

    kw = {}
    if spec_k:
        kw = dict(spec_decode="draft", spec_k=spec_k, draft_model=draft)
    eng = Engine(model, n_slots=n_slots, max_seq_len=max_seq_len,
                 registry=MetricsRegistry(), kv_dtype=kv_dtype,
                 kv_impl=kv_impl, **kw)
    # warmup: every prefill bucket + the decode/spec step compile here
    for p in prompts:
        eng.submit(list(p), max_new_tokens=max_new, temperature=1.0)
    eng.drain()
    reg = MetricsRegistry()
    eng._reg = reg
    eng._tick_n = 0
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        eng.submit(list(p), max_new_tokens=max_new, temperature=1.0,
                   rng=jax.random.key(seed * 1000 + i))
    done = eng.drain()
    wall = time.perf_counter() - t0
    assert all(f.finish_reason == "length" for f in done)
    c = reg.snapshot()["counters"]
    toks = c["tokens_out"]
    decode_s = c["serve_decode_ms"] / 1e3
    proposed = c.get("spec_proposed", 0.0)
    accepted = c.get("spec_accepted", 0.0)
    accept_rate = accepted / proposed if proposed else None
    # per-slot verify passes: spec_proposed counts spec_k per live slot
    # per tick, so proposed/spec_k IS the slot-tick count; sequential
    # emits exactly one token per slot-tick
    slot_ticks = proposed / spec_k if spec_k else toks
    eff_tokens_per_pass = toks / slot_ticks if slot_ticks else 1.0
    row = {
        "spec_decode": "draft" if spec_k else "off",
        "spec_k": spec_k or None,
        "kv_dtype": kv_dtype,
        "kv_impl": kv_impl,
        "tokens_out": toks,
        "decode_ms": c["serve_decode_ms"],
        "decode_tok_per_s": toks / decode_s if decode_s else None,
        "wall_s": wall,
        "accept_rate": accept_rate,
        "eff_tokens_per_pass": eff_tokens_per_pass,
        "verify_ticks": eng._tick_n,
    }
    print(f"[engine] spec={'off' if not spec_k else f'k={spec_k}'}"
          f" kv_dtype={kv_dtype} kv_impl={kv_impl}: "
          f"{row['decode_tok_per_s']:,.0f} decode tok/s"
          + (f"  accept {accept_rate:.2f}  "
             f"{eff_tokens_per_pass:.2f} tok/pass" if spec_k else
             "  1.00 tok/pass"))
    return row


def engine_grid(args):
    """The ISSUE 11 tiny-GPT bench: spec off vs spec_k grid (x kv_dtype)
    through the serve engine, JSON-able for BENCH artifacts."""
    from avenir_tpu.models.gpt import GPT, GPTConfig

    seed = int(args.get("seed", 0))
    vocab = int(args.get("vocab_size", 256))
    max_new = int(args.get("max_new", 48))
    n_slots = int(args.get("n_slots", 8))
    max_seq_len = int(args.get("max_seq_len", 128))
    kv_impl = args.get("kv_impl", "slab")
    spec_ks = [int(k) for k in args.get("spec_ks", "4,8").split(",") if k]
    kv_dtypes = args.get("kv_dtypes", args.get("kv_dtype", "bf16")).split(",")
    # the tiny-GPT bench pair: an 8-layer target, a 1-layer narrow
    # draft — random-init, so the measured accept rate is the near-flat
    # distribution overlap (~0.7 at temperature 1.0), reported honestly
    # in the artifact rather than assumed
    tcfg = GPTConfig(
        block_size=256, vocab_size=vocab,
        n_layer=int(args.get("n_layer", 8)), n_head=4,
        n_embd=int(args.get("n_embd", 128)),
        dropout=0.0, bias=True, attn_impl="xla")
    dcfg = GPTConfig(
        block_size=256, vocab_size=vocab,
        n_layer=int(args.get("draft_layers", 1)), n_head=4,
        n_embd=int(args.get("draft_embd", 64)),
        dropout=0.0, bias=True, attn_impl="xla")
    model = GPT(tcfg, rngs=nnx.Rngs(seed))
    draft = GPT(dcfg, rngs=nnx.Rngs(seed + 7))
    rng = np.random.default_rng(seed)
    prompts = [[int(t) for t in rng.integers(0, vocab, 32)]
               for _ in range(n_slots)]

    rows = []
    for kv_dtype in kv_dtypes:
        for spec_k in [0] + spec_ks:
            rows.append(bench_engine_cell(
                model, draft, spec_k=spec_k, kv_dtype=kv_dtype,
                kv_impl=kv_impl, prompts=prompts, max_new=max_new,
                n_slots=n_slots, max_seq_len=max_seq_len, seed=seed))
    base = {r["kv_dtype"]: r["decode_tok_per_s"] for r in rows
            if r["spec_decode"] == "off"}
    for r in rows:
        r["speedup_vs_off"] = (r["decode_tok_per_s"] / base[r["kv_dtype"]]
                               if base.get(r["kv_dtype"]) else None)
    best = max((r for r in rows if r["spec_k"]),
               key=lambda r: r["speedup_vs_off"] or 0.0, default=None)
    bench = {
        "kind": "decode_bench",
        "config": {
            "seed": seed, "vocab_size": vocab, "max_new": max_new,
            "n_slots": n_slots, "max_seq_len": max_seq_len,
            "target": {"n_layer": tcfg.n_layer, "n_embd": tcfg.n_embd},
            "draft": {"n_layer": dcfg.n_layer, "n_embd": dcfg.n_embd},
            "temperature": 1.0,
        },
        "rows": rows,
        "extra": {
            "kv_dtype": ",".join(kv_dtypes),
            "spec_k": spec_ks,
            "accept_rate": {f"k={r['spec_k']}": r["accept_rate"]
                            for r in rows if r["spec_k"]},
            "eff_tokens_per_pass": {
                f"k={r['spec_k']}" if r["spec_k"] else "off":
                    r["eff_tokens_per_pass"] for r in rows},
            "best_speedup_vs_off": (best or {}).get("speedup_vs_off"),
        },
    }
    for r in rows:
        if r["spec_k"]:
            print(f"  -> spec_k={r['spec_k']} kv_dtype={r['kv_dtype']}: "
                  f"{r['speedup_vs_off']:.2f}x decode tok/s vs off, "
                  f"{r['eff_tokens_per_pass']:.2f} effective "
                  "tokens/model-pass")
    out = args.get("json")
    if out:
        with open(out, "w") as f:
            json.dump(bench, f, indent=1)
        print(f"[engine] wrote {out}")
    return bench


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    if "engine" in args:
        engine_grid(args)
        return
    new_tokens = int(args.get("tokens", 128))
    assert new_tokens >= 2, "--tokens must be >= 2 (delta timing needs two lengths)"
    batch = int(args.get("batch", 1))

    from avenir_tpu.models.gpt import GPT, GPTConfig

    on_tpu = jax.default_backend() == "tpu"
    cdtype = "bfloat16" if on_tpu else "float32"
    gpt = GPT(GPTConfig(block_size=1024, vocab_size=50304, n_layer=12,
                        n_head=12, n_embd=768, dropout=0.0, bias=True,
                        compute_dtype=cdtype, attn_impl="xla"),
              rngs=nnx.Rngs(0))
    bench_one("gpt2-124m decode", gpt, batch=batch, prompt_len=128,
              new_tokens=new_tokens)

    from avenir_tpu.models.llama import Llama, LlamaConfig

    llama = Llama(LlamaConfig(block_size=4096, vocab_size=16384, n_layer=2,
                              n_head=32, n_kv_head=8, n_embd=4096,
                              ffn_hidden=14336, rope_theta=500000.0,
                              compute_dtype=cdtype, attn_impl="xla"),
                  rngs=nnx.Rngs(0))
    bench_one("llama8b-shape (L=2) decode", llama, batch=batch,
              prompt_len=128, new_tokens=new_tokens)


if __name__ == "__main__":
    main()
