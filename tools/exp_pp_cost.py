"""Pipeline cost table (r5, VERDICT r4 missing #4): compiled temp-memory
and analytic bubble fraction vs (p, M, schedule) on the 8-CPU harness.

Temp bytes come from XLA memory_analysis of the jitted fwd+bwd of a
GPT stack on a pipe mesh — the activation-stash difference between the
'gpipe' and 'remat' backward schedules is the quantity 1F1B exists for.

Run: python tools/exp_pp_cost.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# env var alone is not enough: the axon sitecustomize imports jax and
# force-sets jax_platforms before this line runs (see tests/conftest.py)
jax.config.update("jax_platforms", "cpu")

from flax import nnx

from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.parallel.mesh import make_mesh


def temp_mb(p, M, schedule, n_layer=8, n_embd=256, block=512, batch=8):
    cfg = GPTConfig(block_size=block, vocab_size=512, n_layer=n_layer,
                    n_head=4, n_embd=n_embd, dropout=0.0, bias=False,
                    attn_impl="xla", scan_layers=True,
                    pipeline_microbatches=M, pipeline_schedule=schedule)
    mesh = make_mesh(f"pipe:{p}", devices=jax.devices()[:p])
    with jax.set_mesh(mesh):
        graphdef, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)
        x = jax.random.randint(jax.random.key(1), (batch, block), 0, 512)
        y = jax.random.randint(jax.random.key(2), (batch, block), 0, 512)

        def loss_fn(params):
            _, loss = nnx.merge(graphdef, params)(x, targets=y)
            return loss

        comp = jax.jit(jax.grad(loss_fn)).lower(params).compile()
        return comp.memory_analysis().temp_size_in_bytes / 1e6


if __name__ == "__main__":
    print(f"{'p':>3} {'M':>3} {'bubble':>7} {'gpipe MB':>9} "
          f"{'remat MB':>9} {'ratio':>6}")
    for p, M in [(2, 2), (2, 4), (2, 8), (4, 4), (4, 8)]:
        g = temp_mb(p, M, "gpipe")
        r = temp_mb(p, M, "remat")
        bub = (p - 1) / (M + p - 1)
        print(f"{p:>3} {M:>3} {bub:>6.0%} {g:>9.1f} {r:>9.1f} "
              f"{g / r:>6.2f}")
