"""Sweep flash-attention block sizes through the REAL bench.py train step.

Microbenchmarks on the axon-tunneled chip are dominated by per-dispatch
and D2H-fetch overheads (exp_layout.py postmortem) — the only trustworthy
A/B is the full train step. Each config runs bench.py in a subprocess
with AVENIR_FLASH_BLOCKS set and reports the JSON line's tokens/sec.

Usage: python tools/bench_sweep.py [bq,bk,bqb ...]
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_GRID = [
    "512,1024,512",   # round-2 default
    "512,1024,256",
    "1024,1024,1024",
    "1024,1024,512",
    "1024,1024,256",
    "256,1024,256",
]


def run_one(blocks, extra=()):
    env = dict(os.environ, AVENIR_FLASH_BLOCKS=blocks)
    try:
        out = subprocess.run(
            # --form=step: the sweep A/Bs the isolated train-step harness,
            # not the full trainer loop (bench.py's default form)
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--form=step", *extra],
            capture_output=True, text=True, env=env, timeout=1200,
        )
    except subprocess.TimeoutExpired:
        print(f"{blocks}: bench timed out (1200s)", file=sys.stderr)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            return json.loads(line)
    print(out.stdout[-2000:], out.stderr[-2000:], sep="\n", file=sys.stderr)
    return None


def main():
    # leading-dash args pass through to bench.py (e.g. --attn=jax_ref,
    # --batch=8); bare args are block configs "bq,bk,bqb"
    extra = tuple(a for a in sys.argv[1:] if a.startswith("-"))
    grid = [a for a in sys.argv[1:] if not a.startswith("-")] or DEFAULT_GRID
    for blocks in grid:
        r = run_one(blocks, extra)
        if r is None:
            print(f"{blocks:18s} FAILED")
            continue
        print(f"{blocks:18s} {r['value']:10.0f} tok/s  "
              f"mfu={r['extra']['mfu']:.3f}  vs={r['vs_baseline']:.3f}")


if __name__ == "__main__":
    main()
