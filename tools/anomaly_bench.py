"""Detection-latency bench for the fleet health engine (ISSUE 14
acceptance): on fault-injected degradation runs, the anomaly engine
must fire — with evidence and a flight dump — strictly BEFORE the
watchdog/stall tier would, and a steady in-SLO run must produce ZERO
anomalies. Emits BENCH_anomaly.json recording detection latency vs
watchdog/stall latency per scenario.

Scenarios (utils/faults.py sites):

  train_step_degrade   the REAL training loop (tiny CPU config, the
                       bench.py smoke shape) with the
                       `train_step_degrade` site armed: every window
                       adds +2 ms/iter of permanent host latency.
                       Windows keep completing, so the watchdog NEVER
                       fires (its latency is recorded as null =
                       infinity) — the step_time_drift detector is the
                       only tier that sees the rot.
  serve_replica_wedge  a 2-replica fleet with `replica_stall` armed:
                       the victim silently stops beating while holding
                       work. The stall tier declares death at
                       max(stall_floor, 10 x median step); the
                       heartbeat_creep detector fires at
                       max(0.25s, 3 x median step) — strictly earlier
                       by the shared rule's construction. Both
                       latencies are measured from the wedge instant.
  steady_serve         the same fleet, same seeded load, no faults:
                       the no-flapping pin — zero anomalies.

Usage:
    python tools/anomaly_bench.py [--out=BENCH_anomaly.json] [--seed=0]
"""

import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()


def train_degrade_scenario(seed, *, degrade_after=6, max_iters=159):
    """The real train loop under gradual degradation. Returns the
    scenario dict; anomaly latency is measured from the first degraded
    window's iter record to the first `anomaly` record."""
    import shutil

    import numpy as np

    from avenir_tpu.obs.report import load_records
    from avenir_tpu.train.loop import run_training
    from avenir_tpu.utils.faults import FaultInjector, set_injector

    tmp = tempfile.mkdtemp(prefix="avenir-anomaly-bench-")
    prev = set_injector(FaultInjector(
        f"train_step_degrade:p=1:after={degrade_after}", seed=seed))
    try:
        rng = np.random.default_rng(seed)
        rng.integers(0, 50304, 400_000, dtype=np.uint16).tofile(
            f"{tmp}/train.bin")
        rng.integers(0, 50304, 50_000, dtype=np.uint16).tofile(
            f"{tmp}/val.bin")
        K = 4  # short windows: the drift series needs window cadence
        # the model is TINY on purpose: the +2 ms/iter rot must
        # dominate the baseline window wall, or 40 windows of CPU
        # compute noise bury a drift this bench wants visible fast
        cfg = dict(
            out_dir=f"{tmp}/out", eval_interval=100_000, log_interval=4,
            eval_iters=1, eval_only=False, always_save_checkpoint=False,
            init_from="scratch", wandb_log=False, wandb_project="b",
            wandb_run_name="b", dataset=tmp,
            gradient_accumulation_steps=1, batch_size=1, block_size=64,
            model_type="gpt", n_layer=1, n_head=2, n_embd=32,
            dropout=0.0, bias=True, n_kv_head=0, ffn_hidden=0,
            rope_theta=10000.0, n_experts=8, n_experts_per_tok=2,
            capacity_factor=1.25, learning_rate=6e-4,
            max_iters=max_iters, weight_decay=0.1, beta1=0.9,
            beta2=0.95, grad_clip=1.0, decay_lr=False, warmup_iters=10,
            lr_decay_iters=1000, min_lr=6e-5, backend="tpu",
            device="cpu", dtype="float32", compile=False, seed=seed,
            # data:1 works on one real device AND under the test
            # harness's 8 virtual ones (the test_train_tpu idiom)
            mesh_shape="data:1", remat=False, scan_layers=False,
            use_pallas=False, attn_impl="xla", loss_impl="reference",
            loss_chunk=0, fused_adamw=False, profile=False,
            allow_unsharded_fallback=False, dispatch_steps=K,
            metrics_log=True,
            # the stall tier: armed, and silent by design here —
            # windows keep completing while they rot
            watchdog_secs=2.0,
            anomaly_detect=True, anomaly_window_s=0.25,
        )
        os.makedirs(cfg["out_dir"], exist_ok=True)
        run_training(cfg)
        records = load_records(os.path.join(cfg["out_dir"],
                                            "metrics.jsonl"))
        iters = [r for r in records if r.get("kind") == "iter"]
        anomalies = [r for r in records if r.get("kind") == "anomaly"]
        stalls = [r for r in records if r.get("kind") == "stall"]
        # the first degraded window starts at iter degrade_after * K
        # (one injector consult per window)
        first_bad = degrade_after * K
        t_bad = next((r["t"] for r in iters if r["iter"] >= first_bad),
                     None)
        t_anom = anomalies[0]["t"] if anomalies else None
        dumps = glob.glob(os.path.join(cfg["out_dir"],
                                       "flight-anomaly-*.jsonl"))
        return {
            "detector": (anomalies[0].get("detector")
                         if anomalies else None),
            "anomalies": len(anomalies),
            "anomaly_latency_s": (round(t_anom - t_bad, 3)
                                  if t_anom and t_bad else None),
            "watchdog_fired": bool(stalls),
            "watchdog_latency_s": (round(stalls[0]["t"] - t_bad, 3)
                                   if stalls and t_bad else None),
            "flight_dumps": len(dumps),
            "evidence": {k: anomalies[0].get(k) for k in
                         ("value", "baseline", "z", "rel_rise")
                         } if anomalies else None,
            "degrade_after_windows": degrade_after,
            "n_iters": max_iters,
        }
    finally:
        set_injector(prev)
        shutil.rmtree(tmp, ignore_errors=True)


def _build_fleet(seed, reg, tracer, ae, *, stall_floor_s):
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.serve import Router

    model = GPT(GPTConfig(
        block_size=128, vocab_size=256, n_layer=1, n_head=2, n_embd=32,
        dropout=0.0, bias=True, attn_impl="xla"), rngs=nnx.Rngs(seed))
    return Router(model, n_replicas=2, n_slots=2, registry=reg,
                  seed=seed, tracer=tracer, anomaly=ae,
                  stall_floor_secs=stall_floor_s)


def serve_scenario(seed, *, wedge, stall_floor_s=1.5, n_requests=64):
    """A small real-time fleet run; with `wedge` the replica_stall
    site wedges a busy replica and we time (a) the heartbeat_creep
    anomaly and (b) the stall tier's death declaration, both from the
    wedge instant."""
    import numpy as np

    from avenir_tpu.obs import MetricsRegistry, Tracer
    from avenir_tpu.obs.anomaly import AnomalyEngine
    from avenir_tpu.utils.faults import FaultInjector, set_injector

    tmp = tempfile.mkdtemp(prefix="avenir-anomaly-serve-")
    reg = MetricsRegistry()
    tracer = Tracer(registry=reg, out_dir=tmp)
    ae = AnomalyEngine(registry=reg, tracer=tracer, window_s=0.25)
    prev = set_injector(FaultInjector(
        "replica_stall:p=1:after=30:n=1" if wedge else "", seed=seed))
    try:
        router = _build_fleet(seed, reg, tracer, ae,
                              stall_floor_s=stall_floor_s)
        rng = np.random.default_rng(seed)
        prompts = [[int(t) for t in rng.integers(0, 256,
                                                 int(rng.integers(4, 12)))]
                   for _ in range(n_requests)]
        t_wedge = t_anom = t_dead = None
        submitted = 0
        done = 0
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            # keep a trickle of work in flight so BOTH replicas hold
            # work (a wedged-but-idle replica is exempt by design)
            while submitted < n_requests and router.queue_depth < 4:
                router.submit(prompts[submitted], max_new_tokens=16,
                              temperature=1.0, top_k=None)
                submitted += 1
            done += len(router.step())
            if t_wedge is None and any(
                    getattr(r, "_stalled", False)
                    for r in router.replicas):
                t_wedge = time.perf_counter()
            if t_anom is None and ae.fired:
                t_anom = time.perf_counter()
            if t_dead is None and any(r.state == "dead"
                                      for r in router.replicas):
                t_dead = time.perf_counter()
            if wedge and t_dead is not None and t_anom is not None:
                break
            if not wedge and done >= n_requests:
                break
            time.sleep(0.02)
        router.close()
        counters = reg.snapshot()["counters"]
        dumps = glob.glob(os.path.join(tmp, "flight-anomaly-*.jsonl"))
        out = {
            "anomalies": int(counters.get("anomaly", 0)),
            "suppressed": int(counters.get("anomalies_suppressed", 0)),
            "flight_dumps": len(dumps),
            "served": done,
        }
        if wedge:
            out.update({
                "detector": (ae.fired[0]["detector"] if ae.fired
                             else None),
                "evidence": ({k: ae.fired[0].get(k) for k in
                              ("value", "threshold", "median_step_ms")}
                             if ae.fired else None),
                "anomaly_latency_s": (round(t_anom - t_wedge, 3)
                                      if t_anom and t_wedge else None),
                "stall_latency_s": (round(t_dead - t_wedge, 3)
                                    if t_dead and t_wedge else None),
                "stall_floor_s": stall_floor_s,
            })
            if out["anomaly_latency_s"] and out["stall_latency_s"]:
                out["lead_s"] = round(out["stall_latency_s"]
                                      - out["anomaly_latency_s"], 3)
                out["lead_frac"] = round(
                    1.0 - out["anomaly_latency_s"]
                    / out["stall_latency_s"], 4)
        return out
    finally:
        set_injector(prev)
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    seed = int(args.get("seed", 0))
    out_path = args.get("out", "BENCH_anomaly.json")

    print("[anomaly_bench] scenario 1/3: train_step_degrade "
          "(real train loop, gradual +2ms/iter rot)")
    train = train_degrade_scenario(seed)
    print(f"  anomaly after {train['anomaly_latency_s']}s "
          f"({train['detector']}); watchdog fired: "
          f"{train['watchdog_fired']} (gradual rot never stalls)")

    print("[anomaly_bench] scenario 2/3: serve_replica_wedge "
          "(silent wedge; anomaly vs stall tier)")
    wedge = serve_scenario(seed, wedge=True)
    print(f"  anomaly at +{wedge.get('anomaly_latency_s')}s vs stall "
          f"tier at +{wedge.get('stall_latency_s')}s "
          f"(lead {wedge.get('lead_s')}s)")

    print("[anomaly_bench] scenario 3/3: steady_serve (no faults — "
          "the zero-anomaly pin)")
    steady = serve_scenario(seed, wedge=False)
    print(f"  anomalies: {steady['anomalies']} over "
          f"{steady['served']} served")

    ok = (
        train["anomalies"] >= 1
        and not train["watchdog_fired"]          # rot never stalls
        and train["flight_dumps"] >= 1
        and wedge.get("anomaly_latency_s") is not None
        and wedge.get("stall_latency_s") is not None
        and wedge["anomaly_latency_s"] < wedge["stall_latency_s"]
        and wedge["flight_dumps"] >= 1
        and steady["anomalies"] == 0
    )
    bench = {
        "kind": "anomaly_bench",
        "config": {"seed": seed},
        "scenarios": {
            "train_step_degrade": train,
            "serve_replica_wedge": wedge,
            "steady_serve": steady,
        },
        "note": (
            "detection latency vs watchdog/stall latency per scenario, "
            "measured from the fault instant. train: the watchdog "
            "NEVER fires on gradual rot (latency null = infinity) — "
            "only the drift detector sees it. serve: heartbeat_creep "
            "fires at 3x the median step vs the stall tier's 10x (the "
            "shared stall_threshold_secs rule at a smaller factor), "
            "so 'strictly before' holds by construction."),
        "ok": bool(ok),
    }
    with open(out_path, "w") as f:
        json.dump(bench, f, indent=1)
    print(f"[anomaly_bench] -> {out_path} (ok={ok})")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
