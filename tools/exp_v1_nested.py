"""The r5 nested-wrap grad-exactness harness: real GPT fwd+bwd on a
pipe x data mesh, pallas vs xla attention, each against the
single-device oracle.

History: during round 5 this script (driven by a temporary
AVENIR_FLASH_NEST env hack in the dispatcher, since removed) REPRODUCED
the r4 cotangent bug at 2.8e-3 — a nested shard_map naming the Manual
'pipe' axis psums cotangents across stages — and then verified the fix
(axis_names=free_axis_names: 1.0e-8). The product now always nests with
the free-axes rule, so running this today checks the shipped path:
expect ~1e-8 for both attention impls on any mesh.

Run: python tools/exp_v1_nested.py [mesh_shape] [--perleaf]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", "cpu")

from flax import nnx

from avenir_tpu.parallel.mesh import make_mesh


def grads(mesh_shape, attn_impl):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import setup_state

    cfg = make_cfg("x", "y", mesh_shape=mesh_shape or "data:1",
                   scan_layers=True, attn_impl=attn_impl,
                   allow_unsharded_fallback=True,
                   pipeline_microbatches=2)
    mesh = make_mesh(mesh_shape or "data:1")
    model_args = dict(n_layer=2, n_head=4, n_embd=32, block_size=64,
                      bias=False, vocab_size=96, dropout=0.0)
    st = setup_state(cfg, mesh, model_args, verbose=False)
    x = jax.random.randint(jax.random.key(1), (8, 64), 0, 96)
    y = jax.random.randint(jax.random.key(2), (8, 64), 0, 96)
    graphdef = st["graphdef"]

    def loss_fn(params):
        model = nnx.merge(graphdef, params)
        _, loss = model(x, targets=y)
        return loss

    with jax.set_mesh(mesh):
        params = jax.jit(lambda: nnx.split(st["ctor"](0), nnx.Param)[1],
                         out_shardings=st["shard_tree"])()
        g = jax.jit(jax.grad(loss_fn))(params)
        return jax.tree.map(np.asarray, nnx.to_pure_dict(g))


def maxdiff(a, b):
    leaves_a = jax.tree.leaves(a)
    leaves_b = jax.tree.leaves(b)
    return max(float(np.max(np.abs(x - y)))
               for x, y in zip(leaves_a, leaves_b))


def perleaf(a, b):
    fa = dict(jax.tree_util.tree_flatten_with_path(a)[0] and [])
    pa, _ = jax.tree_util.tree_flatten_with_path(a)
    pb, _ = jax.tree_util.tree_flatten_with_path(b)
    for (ka, xa), (_, xb) in zip(pa, pb):
        d = float(np.max(np.abs(xa - xb)))
        r = float(np.max(np.abs(xa - xb) / (np.abs(xb) + 1e-8)))
        name = jax.tree_util.keystr(ka)
        print(f"    {name:60s} abs {d:.2e}  rel {r:.2e}")


if __name__ == "__main__":
    mesh_shape = sys.argv[1] if len(sys.argv) > 1 else "pipe:2,data:2"
    ref = grads(None, "xla")
    mesh_xla = grads(mesh_shape, "xla")
    mesh_pl = grads(mesh_shape, "pallas")
    print(f"mesh={mesh_shape}")
    print(f"  xla-on-mesh  vs single-dev oracle: {maxdiff(mesh_xla, ref):.2e}")
    print(f"  pallas-on-mesh vs single-dev oracle: {maxdiff(mesh_pl, ref):.2e}")
    if "--perleaf" in sys.argv:
        perleaf(mesh_pl, ref)
