"""Chaos harness: SIGKILL a real training job over and over and prove
resume is EXACT (ISSUE 5 tentpole, part 4).

The crash-consistency claim this repo makes is concrete: any SIGKILL —
between steps, mid-eval, or mid-checkpoint-save — loses at most the
work since the last committed checkpoint, and the relaunched run's loss
trajectory is BIT-IDENTICAL to a never-interrupted run's (the loader
fast-forwards its rng stream on resume; step rngs are iteration-
indexed; saves are commit-marked). This tool is the proof:

  1. run the job uninterrupted, record every logged loss;
  2. run it again, SIGKILLing it `--kills` times at seeded-random
     trigger points (roughly half aimed at the "saving checkpoint"
     window to hit mid-save), relaunching with --init_from=resume;
  3. assert the union of logged (iter, loss) pairs matches the
     uninterrupted run's EXACTLY, bit for bit;
  4. optional corruption drill (--drill=all|corruption): flip one byte
     in the newest committed checkpoint, resume, and assert the restore
     fell back to the previous generation (`ckpt_fallback` recorded in
     the JSONL run log).

Emits a BENCH-style JSON report (kills survived, resume sources,
fallbacks taken, io retries, bit_identical verdict); exits non-zero if
any assertion fails, so CI can gate on it.

    python tools/chaos_train.py --seed=0 --kills=10 --max_iters=24
    python tools/chaos_train.py --drill=corruption --out=chaos.json

Inject extra storage faults into the children with --faults=SPEC
(forwarded as AVENIR_FAULTS, e.g. --faults=ckpt_write_fail:p=0.5:n=2 —
the retry/backoff layer must absorb them; see avenir_tpu/utils/faults).
"""

import json
import os
import random
import select
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _parse_args():
    return {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}


def _cli(data_dir, out_dir, cfg, extra):
    args = dict(
        dataset=data_dir, out_dir=out_dir, backend="tpu", device="cpu",
        compile=False, eval_interval=cfg["eval_interval"], eval_iters=2,
        log_interval=1, batch_size=4, block_size=32, n_layer=2, n_head=2,
        n_embd=32, dropout=0.0, gradient_accumulation_steps=2,
        always_save_checkpoint=True, warmup_iters=2, lr_decay_iters=200,
        learning_rate=1e-3, use_pallas=False, mesh_shape="data:1",
        max_iters=cfg["max_iters"], keep_checkpoints=cfg["keep"],
        metrics_log=True, dtype="float32",
    )
    args.update(cfg.get("extra_args") or {})
    args.update(extra)
    return [sys.executable, "train.py"] + [f"--{k}={v}"
                                           for k, v in args.items()]


def _env(cfg):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    if cfg["faults"]:
        env["AVENIR_FAULTS"] = cfg["faults"]
        env["AVENIR_FAULTS_SEED"] = str(cfg["seed"])
    return env


def _run_to_completion(data_dir, out_dir, cfg, extra, timeout=900):
    r = subprocess.run(_cli(data_dir, out_dir, cfg, extra), cwd=REPO,
                       env=_env(cfg), capture_output=True, text=True,
                       timeout=timeout)
    assert r.returncode == 0, (
        f"training run failed ({r.returncode}):\n{r.stdout}\n{r.stderr}")
    return r.stdout


def _kill_one(data_dir, out_dir, cfg, extra, trigger, rng, timeout=900):
    """Launch a run and SIGKILL it when `trigger` fires (plus a small
    random delay, to land INSIDE the triggered phase). Triggers are
    RELATIVE so a resumed segment always gets killed while it is still
    making progress: ("iters", n) kills after the n-th new `iter` log
    line of THIS segment, ("line", s) on the first line containing s
    (e.g. "saving checkpoint" for the mid-save window). Returns
    (killed, stdout_so_far) — killed=False means the segment completed
    before the trigger."""
    proc = subprocess.Popen(
        _cli(data_dir, out_dir, cfg, extra), cwd=REPO, env=_env(cfg),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    mode, arg = trigger
    buf = ""
    seen_iters = 0
    deadline = time.time() + timeout
    try:
        while proc.poll() is None and time.time() < deadline:
            ready, _, _ = select.select([proc.stdout], [], [], 1.0)
            if not ready:
                continue
            line = proc.stdout.readline()
            buf += line
            if mode == "iters" and line.startswith("iter "):
                seen_iters += 1
            hit = (seen_iters >= arg if mode == "iters"
                   else arg in line)
            if hit:
                time.sleep(rng.uniform(0.0, 0.05))
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=60)
                return True, buf
        if proc.poll() is None:  # never hit the trigger in time
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=60)
            return True, buf
        return False, buf + proc.stdout.read()  # completed before trigger
    finally:
        if proc.poll() is None:
            proc.kill()


def _trajectory(metrics_path):
    """{iter: loss} from every `iter` record across ALL log segments
    (a resumed run appends; re-run iters overwrite — determinism makes
    first and last occurrence identical, asserted by the caller's
    comparison against the uninterrupted run)."""
    from avenir_tpu.obs.report import load_records

    out = {}
    for r in load_records(metrics_path):
        if r.get("kind") == "iter":
            out[r["iter"]] = r["loss"]
    return out


def _log_counters(metrics_path):
    """Summed fault-tolerance counters + restore records across every
    segment of a (possibly many-times-killed) run log. Counters are
    cumulative per segment, so the per-segment MAX is the segment's
    total; segments reset on relaunch, so totals sum across segments."""
    from avenir_tpu.obs.report import load_records

    keys = ("io_retries", "ckpt_fallback", "ckpt_corrupt_detected",
            "ckpt_save_errors")
    totals = dict.fromkeys(keys, 0.0)
    seg = dict.fromkeys(keys, 0.0)
    restores = []
    retries = 0
    for r in load_records(metrics_path):
        kind = r.get("kind")
        if kind == "run_meta":  # new segment: bank the previous one
            for k in keys:
                totals[k] += seg[k]
            seg = dict.fromkeys(keys, 0.0)
        elif kind == "restore":
            restores.append({"iter": r.get("iter"),
                             "source_kind": r.get("source_kind"),
                             "skipped_bad": r.get("skipped_bad", 0)})
            for k in keys:
                seg[k] = max(seg[k], float(
                    (r.get("counters") or {}).get(k, 0.0)))
        elif kind == "retry":
            retries += 1
        else:
            for k in keys:
                seg[k] = max(seg[k], float(
                    (r.get("counters") or {}).get(k, 0.0)))
    for k in keys:
        totals[k] += seg[k]
    totals["retry_records"] = retries
    totals["restores"] = restores
    return totals


def _build_mixed_corpus(work, *, seed=7):
    """Two corpora carved from ONE synthetic text (so they share a
    stoi/vocab): 'owt' in the sharded MANIFEST layout, 'code' as a
    legacy single-file dir — the kill-resume proof then covers sharded
    reads, legacy reads, AND per-corpus mixed-stream replay in one run.
    Returns the dir train.py gets as --dataset ('code' resolves as its
    sibling via the data_mix name resolution)."""
    import shutil

    import numpy as np

    from avenir_tpu.data.loader import read_wire_format
    from avenir_tpu.data.streaming import write_token_shards
    from avenir_tpu.utils.corpus import synthetic_corpus, write_char_dataset

    base = os.path.join(work, "data-base")
    owt = os.path.join(work, "owt")
    code = os.path.join(work, "code")
    if os.path.isdir(os.path.join(owt, "train.shards")):
        return owt  # reused workdir
    write_char_dataset(base, synthetic_corpus(n_chars=60_000, seed=seed))
    for name, d in (("owt", owt), ("code", code)):
        os.makedirs(d, exist_ok=True)
        for split in ("train", "val"):
            src = os.path.join(base, f"{split}.bin")
            dt, off = read_wire_format(src)
            arr = np.fromfile(src, dtype=dt, offset=off)
            half = len(arr) // 2
            if name == "owt":
                write_token_shards(os.path.join(d, f"{split}.shards"),
                                   arr[:half], shard_tokens=4096)
            else:
                arr[half:].tofile(os.path.join(d, f"{split}.bin"))
        shutil.copy(os.path.join(base, "meta.pkl"),
                    os.path.join(d, "meta.pkl"))
    return owt


def _flip_byte(path, rng):
    with open(path, "r+b") as f:
        f.seek(0, 2)
        size = f.tell()
        pos = rng.randrange(size)
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
    return pos


def main():
    t_start = time.time()
    a = _parse_args()
    cfg = {
        "seed": int(a.get("seed", 0)),
        "kills": int(a.get("kills", 10)),
        "max_iters": int(a.get("max_iters", 24)),
        "eval_interval": int(a.get("eval_interval", 4)),
        "keep": int(a.get("keep", 2)),
        "faults": a.get("faults", ""),
        "drill": a.get("drill", "kills"),  # kills | corruption | all
        "out": a.get("out", ""),
        "workdir": a.get("workdir", ""),
        # --mix=1: run the whole drill on a weighted two-corpus mixture
        # (one sharded, one legacy layout) with deep prefetch — the
        # ISSUE 19 streaming loader's kill-resume proof
        "mix": a.get("mix", "") not in ("", "0"),
        "prefetch_depth": int(a.get("prefetch_depth", 3)),
    }
    rng = random.Random(cfg["seed"])
    import tempfile

    work = cfg["workdir"] or tempfile.mkdtemp(prefix="avenir-chaos-")
    os.makedirs(work, exist_ok=True)
    if cfg["mix"]:
        data_dir = _build_mixed_corpus(work)
        cfg["extra_args"] = {"data_mix": "owt:0.65,code:0.35",
                             "prefetch_depth": cfg["prefetch_depth"]}
    else:
        data_dir = os.path.join(work, "data")
        if not os.path.exists(os.path.join(data_dir, "train.bin")):
            from avenir_tpu.utils.corpus import (synthetic_corpus,
                                                 write_char_dataset)

            write_char_dataset(data_dir,
                               synthetic_corpus(n_chars=60_000, seed=7))

    report = {"tool": "chaos_train", "seed": cfg["seed"],
              "config": {k: cfg[k] for k in
                         ("kills", "max_iters", "eval_interval", "keep",
                          "faults", "drill", "mix", "prefetch_depth")},
              "kills": [], "ok": True}

    if cfg["drill"] in ("kills", "all"):
        print(f"[chaos] baseline uninterrupted run -> {work}/base")
        base_out = os.path.join(work, "base")
        _run_to_completion(data_dir, base_out, cfg, {})
        base_traj = _trajectory(os.path.join(base_out, "metrics.jsonl"))
        assert base_traj, "baseline run logged no iters"

        chaos_out = os.path.join(work, "chaos")
        kills_done = 0
        while kills_done < cfg["kills"]:
            have_ckpt = (
                os.path.exists(os.path.join(chaos_out, "ckpt.pt"))
                or os.path.exists(os.path.join(chaos_out, "MANIFEST.json"))
                or os.path.isdir(os.path.join(chaos_out, "ckpt-gens")))
            extra = {"init_from": "resume"} if have_ckpt else {}
            mid_save = rng.random() < 0.5
            trigger = (("line", "saving checkpoint") if mid_save else
                       ("iters",
                        rng.randrange(1, 2 * cfg["eval_interval"])))
            killed, _ = _kill_one(data_dir, chaos_out, cfg, extra,
                                  trigger, rng)
            report["kills"].append({
                "n": kills_done, "trigger": list(trigger),
                "mid_save": mid_save,
                "resumed": bool(extra), "killed": killed,
            })
            print(f"[chaos] kill {kills_done + 1}/{cfg['kills']}: "
                  f"trigger={trigger!r} killed={killed} "
                  f"resumed={bool(extra)}")
            kills_done += 1
            if not killed:
                # the run completed before the trigger; wipe nothing —
                # further relaunches just resume to completion instantly
                continue
        print("[chaos] final relaunch to completion")
        _run_to_completion(data_dir, chaos_out, cfg,
                           {"init_from": "resume"}
                           if os.path.exists(os.path.join(chaos_out,
                                                          "ckpt.pt"))
                           or os.path.isdir(os.path.join(chaos_out,
                                                         "ckpt-gens"))
                           else {})
        chaos_traj = _trajectory(os.path.join(chaos_out, "metrics.jsonl"))
        mismatches = {
            i: (base_traj[i], chaos_traj.get(i))
            for i in base_traj
            if chaos_traj.get(i) != base_traj[i]
        }
        stats = _log_counters(os.path.join(chaos_out, "metrics.jsonl"))
        report.update({
            "baseline_final_loss": base_traj[max(base_traj)],
            "final_loss": chaos_traj.get(max(base_traj)),
            "iters_compared": len(base_traj),
            "bit_identical": not mismatches,
            "mismatches": {str(k): v for k, v in
                           list(mismatches.items())[:10]},
            **stats,
        })
        report["ok"] &= not mismatches
        print(f"[chaos] {len(base_traj)} iters compared, bit_identical="
              f"{not mismatches}, restores={len(stats['restores'])}, "
              f"io_retries={stats['io_retries']:.0f}")

    if cfg["drill"] in ("corruption", "all"):
        cor_out = os.path.join(work, "corrupt")
        print(f"[chaos] corruption drill -> {cor_out}")
        _run_to_completion(data_dir, cor_out, cfg, {})
        pos = _flip_byte(os.path.join(cor_out, "ckpt.pt"), rng)
        out = _run_to_completion(
            data_dir, cor_out, cfg,
            {"init_from": "resume",
             "max_iters": cfg["max_iters"] + cfg["eval_interval"]})
        stats = _log_counters(os.path.join(cor_out, "metrics.jsonl"))
        fell_back = (stats["ckpt_fallback"] >= 1
                     and any(r["skipped_bad"] >= 1
                             for r in stats["restores"]))
        report["corruption_drill"] = {
            "flipped_byte_at": pos,
            "ckpt_fallback": stats["ckpt_fallback"],
            "ckpt_corrupt_detected": stats["ckpt_corrupt_detected"],
            "fell_back": fell_back,
            "resumed_output_has_fallback_line": "FALLBACK" in out,
        }
        report["ok"] &= fell_back
        print(f"[chaos] corruption drill: fell_back={fell_back} "
              f"(corrupt_detected={stats['ckpt_corrupt_detected']:.0f})")

    report["wall_s"] = round(time.time() - t_start, 1)
    line = json.dumps(report)
    print(line)
    if cfg["out"]:
        with open(cfg["out"], "w") as f:
            f.write(line + "\n")
    sys.exit(0 if report["ok"] else 1)


if __name__ == "__main__":
    main()
