"""Round-3 layout experiment #2: BH-major attention block end-to-end.

Compares, in ONE jit call over 12 layers (so the per-dispatch tunnel
overhead amortizes), the full attention sub-block (qkv proj -> attention
-> out proj) in two formulations:

  A. current model form: Linear(C,3C) -> reshape (B,T,H,D) ->
     flash_attention (transposes to (B*H,T,D) inside) -> reshape ->
     Linear(C,C)
  B. BH-major: einsum('btc,chd->bhtd') projections produce the kernel's
     native layout directly (XLA fuses the transpose into the matmul
     epilogue / dot dimension numbers), kernel runs transpose-free, out
     proj consumes (B,H,T,D) via einsum('bhtd,hdc->btc').

Parameters are bitwise-identical between the two forms (B reshapes A's),
so outputs must match and only layout handling differs.

Usage: python tools/exp_layout2.py
"""

import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from avenir_tpu.ops.pallas.flash_attention import (
    _build_flash_fast,
    flash_attention,
)

B, T, H, D = 16, 1024, 12, 64
C = H * D
L = 12


def timeit(fn, *args, warmup=3, iters=10):
    # block_until_ready returns early through the axon tunnel; a D2H fetch
    # of one element is the only reliable fence (same as exp_layout.py).
    for _ in range(warmup):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / iters


def make_params():
    rng = np.random.default_rng(0)
    mk = lambda *s: jnp.asarray(rng.standard_normal(s).astype(np.float32)
                                * 0.02, jnp.bfloat16)
    return [dict(w_qkv=mk(C, 3 * C), b_qkv=mk(3 * C),
                 w_o=mk(C, C), b_o=mk(C)) for _ in range(L)]


def block_a(p, x):
    qkv = x @ p["w_qkv"] + p["b_qkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, D)
    k = k.reshape(B, T, H, D)
    v = v.reshape(B, T, H, D)
    y = flash_attention(q, k, v, causal=True)
    y = y.reshape(B, T, C)
    return x + (y @ p["w_o"] + p["b_o"])


def block_b(p, x):
    wq, wk, wv = jnp.split(p["w_qkv"], 3, axis=1)
    bq, bk, bv = jnp.split(p["b_qkv"], 3)
    # (B,T,C) x (C,H,D) -> (B,H,T,D): transpose rides the matmul output
    q = jnp.einsum("btc,chd->bhtd", x, wq.reshape(C, H, D),
                   preferred_element_type=jnp.bfloat16) + bq.reshape(H, D)[None, :, None, :]
    k = jnp.einsum("btc,chd->bhtd", x, wk.reshape(C, H, D),
                   preferred_element_type=jnp.bfloat16) + bk.reshape(H, D)[None, :, None, :]
    v = jnp.einsum("btc,chd->bhtd", x, wv.reshape(C, H, D),
                   preferred_element_type=jnp.bfloat16) + bv.reshape(H, D)[None, :, None, :]
    sm = 1.0 / math.sqrt(D)
    f = _build_flash_fast(T, True, sm, 512, 1024, False, H, H)
    o = f(q.reshape(B * H, T, D), k.reshape(B * H, T, D),
          v.reshape(B * H, T, D)).reshape(B, H, T, D)
    y = jnp.einsum("bhtd,hdc->btc", o, p["w_o"].reshape(H, D, C),
                   preferred_element_type=jnp.bfloat16) + p["b_o"]
    return x + y


def trunk(block, params, x):
    for p in params:
        x = block(p, x)
    return x


def main():
    params = make_params()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((B, T, C)).astype(np.float32) * 0.3,
                    jnp.bfloat16)

    for name, blk in (("A current (Linear+reshape)", block_a),
                      ("B BH-major einsum", block_b)):
        def loss(params_, x_):
            return trunk(blk, params_, x_).astype(jnp.float32).mean()

        g = jax.jit(jax.grad(loss, argnums=(0, 1)))
        t = timeit(lambda: g(params, x))
        print(f"{name:32s} 12-layer fwd+bwd: {t*1e3:8.2f} ms")

    # parity check
    oa = jax.jit(lambda p_, x_: trunk(block_a, p_, x_))(params, x)
    ob = jax.jit(lambda p_, x_: trunk(block_b, p_, x_))(params, x)
    err = float(jnp.max(jnp.abs(oa.astype(jnp.float32) - ob.astype(jnp.float32))))
    print(f"max |A-B| = {err:.3e}")


if __name__ == "__main__":
    main()
