"""Ladder rungs 3-5 on the real chip (BASELINE.json:9-11; VERDICT r1 item 6).

The full configs cannot fit one 16GB v5e: training state alone is
~12 bytes/param fp32 (params + adam mu/nu) plus fp32 grads during the
step (~16-20 B/param) — 1.5B needs ~25GB, Llama-8B ~130GB, Mixtral-8x7B
~750GB. Those run multi-chip via FSDP/EP (dryrun_multichip validates the
shardings). This tool measures the largest SAME-SHAPE variants that fit a
single chip (matmul widths, head layout, expert count preserved; depth /
vocab reduced — each deviation printed), producing real tok/s + MFU rows
for BASELINE.md.

Per-rung default batches are the r4 single-chip sweep winners
(BASELINE.md "r4 batch sweep"): 1.5B B=8, Llama T=4096 B=5, LONG-T B=2,
Mixtral B=32 — each sits just under the HBM cliff; remat_policy
defaults to dots except Mixtral (nothing — dots measured 14% slower
there).

Usage: python tools/bench_ladder.py [--steps=8]
         [--rung=1p5b|llama8b|llama8b-longT|mixtral]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def run_rung(name, family, cfg_kwargs, batch, steps, flops_per_token=None,
             active_params=None):
    from flax import nnx

    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_multi_train_step, make_step_fns

    if family == "gpt":
        from avenir_tpu.models.gpt import GPT, GPTConfig

        cfg = GPTConfig(**cfg_kwargs)
        ctor = GPT
    elif family == "llama":
        from avenir_tpu.models.llama import Llama, LlamaConfig

        cfg = LlamaConfig(**cfg_kwargs)
        ctor = Llama
    else:
        from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

        cfg = MixtralConfig(**cfg_kwargs)
        ctor = Mixtral

    model = ctor(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)
    n_params = sum(int(np.prod(v.get_value().shape))
                   for _, v in params.flat_state())
    if flops_per_token is None:
        from avenir_tpu.models.common import transformer_flops_per_token

        # exact instantiated param count (active_params adjusts for MoE:
        # dense-equivalent FLOPs only count the K routed experts)
        n_eff = active_params(n_params) if active_params else n_params
        flops_per_token = transformer_flops_per_token(
            n_eff, cfg.n_layer, cfg.n_head,
            cfg.n_embd // cfg.n_head, cfg.block_size,
        )
    tx, _ = make_optimizer(params, learning_rate=3e-4, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=10, lr_decay_iters=1000, min_lr=3e-5)
    opt_state = jax.jit(tx.init)(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    # `steps` optimizer steps per dispatch + pipelined rounds (round 4,
    # same form as bench.py): the next round is dispatched BEFORE the
    # previous round's loss fence, so neither per-step dispatch latency
    # (~9ms on the tunneled host) nor the ~100ms D2H RTT is billed to the
    # rung — the r3 single-dispatch ladder understated heavy rungs 5-10%.
    step = jit_multi_train_step(step_fn, tx)

    T = cfg.block_size
    rng = np.random.default_rng(0)
    V = cfg.vocab_size
    x = jax.numpy.asarray(
        rng.integers(0, V, (steps, 1, batch, T)).astype(np.int32))
    y = jax.numpy.asarray(
        rng.integers(0, V, (steps, 1, batch, T)).astype(np.int32))
    key = jax.random.key(0)

    from avenir_tpu.utils.benching import median_low, time_pipelined_rounds

    p, o, m = step(params, opt_state, key, x, y)  # warmup / compile
    float(m["loss"][-1])  # fence (axon: D2H readback, not block_until_ready)
    st = [p, o]

    def dispatch():
        st[0], st[1], m = step(st[0], st[1], key, x, y)
        return m

    rounds = time_pipelined_rounds(dispatch, lambda m: float(m["loss"][-1]),
                                   n_rounds=3)
    dt = median_low(rounds)
    toks = batch * T * steps / dt

    from avenir_tpu.models.common import tpu_peak_flops

    mfu = toks * flops_per_token / tpu_peak_flops()
    print(f"{name}: params={n_params/1e9:.3f}B batch={batch} T={T} "
          f"tok/s/chip={toks:,.0f} mfu={mfu*100:.1f}%")
    return toks, mfu


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    steps = int(args.get("steps", 8))
    which = args.get("rung", "all")
    batch_override = int(args["batch"]) if "batch" in args else None
    scan_override = None
    if "scan" in args:
        scan_override = args["scan"] in ("1", "True", "true")
    # per-rung default unless --scan was passed
    scan = lambda default: default if scan_override is None else scan_override
    # dots is the measured winner on the dense remat rungs (Mixtral
    # pins its own policy below); --remat_policy=nothing to compare
    remat_policy = args.get("remat_policy", "dots")

    if which in ("all", "1p5b"):
        # GPT-2 1.5B shape: d=1600, 25 heads (BASELINE.json:9). Full 48
        # layers = 1.56B params = ~25GB state; 16 layers (0.57B) fits.
        L, d, h, T = 16, 1600, 25, 1024
        run_rung(
            "gpt2-1.5b-shape (L=48->16, d/heads/T full)", "gpt",
            dict(block_size=T, vocab_size=50304, n_layer=L, n_head=h,
                 n_embd=d, dropout=0.0, bias=True, compute_dtype="bfloat16",
                 attn_impl="pallas",
                 # loop (not scan) is this rung's measured winner
                 scan_layers=scan(False), remat=True,
                 remat_policy=remat_policy),
            batch=batch_override or 8, steps=steps,
        )

    # Llama-3 8B shape: d=4096 ffn=14336 GQA 32/8 (BASELINE.json:10).
    # Full: 32 layers vocab 128256 = 8B params (~130GB state). Fits:
    # 2 layers + vocab 16384 (0.57B). One shared shape dict so the two
    # T variants stay same-shape comparable.
    llama_shape = dict(vocab_size=16384, n_layer=2, n_head=32, n_kv_head=8,
                       n_embd=4096, ffn_hidden=14336, rope_theta=500000.0,
                       compute_dtype="bfloat16", attn_impl="pallas",
                       scan_layers=scan(True), remat=True,
                       remat_policy=remat_policy)

    if which in ("all", "llama8b"):
        # T=4096: single-KV-block fast path (fused bwd)
        run_rung(
            "llama3-8b-shape (L=32->2, vocab->16k, d/ffn/GQA/long-T full)",
            "llama", dict(block_size=4096, **llama_shape),
            batch=batch_override or 5, steps=steps,
        )

    if which in ("all", "llama8b-longT"):
        # Llama-3's NATIVE 8192 context: exercises the blocked
        # (grid-streamed online-softmax) attention path on chip
        run_rung(
            "llama3-8b-shape LONG-T blocked path (T=8192, L=2, vocab 16k)",
            "llama", dict(block_size=8192, **llama_shape),
            batch=batch_override or 2, steps=steps,
        )

    if which in ("all", "mixtral"):
        # Mixtral-8x7B shape: d=4096 ffn=14336 E=8 K=2 (BASELINE.json:11).
        # Full: 47B params. Fits: d=2048 ffn=7168 keeps the E=8/K=2 routed
        # structure and expert einsum shape family at 1 layer (0.44B).
        L, d, hq, hkv, ffn, E, K, T, V = 1, 2048, 16, 4, 7168, 8, 2, 1024, 16384
        run_rung(
            "mixtral-shape (E=8 K=2 kept; d->2048 ffn->7168 L=1 vocab->16k)",
            "mixtral",
            dict(block_size=T, vocab_size=V, n_layer=L, n_head=hq,
                 n_kv_head=hkv, n_embd=d, ffn_hidden=ffn, n_experts=E,
                 n_experts_per_tok=K, capacity_factor=1.25,
                 rope_theta=10000.0, compute_dtype="bfloat16",
                 attn_impl="pallas",
                 scan_layers=scan(False), remat=True,
                 # dots HURTS this rung (B=32: 83.0k vs 96.0-96.6k,
                 # r4 measured) — saving expert-matmul outputs for 8
                 # experts costs the HBM the batch dilution needs
                 remat_policy=args.get("remat_policy", "nothing")),
            batch=batch_override or 32, steps=steps,
            # MFU on ACTIVE params: subtract the (E-K) unrouted experts
            active_params=lambda n: n - L * 3 * d * ffn * (E - K),
        )


if __name__ == "__main__":
    main()
