"""Input-pipeline bench (ISSUE 19): the streaming loader vs the seed
loader, on the numbers that decide whether the pod eats or starves.

Two measurements per seed, same synthetic corpus in both layouts:

  staging throughput   tokens/s of raw batch assembly (`_sample_local`
                       in a tight loop, no prefetch, no cadence): the
                       seed loader's per-crop python slice loop vs the
                       streaming loader's single fused fancy-index
                       gather over the sharded layout.
  input-stall fraction fraction of wall time the consumer spends
                       BLOCKED in get_batch_window at a simulated
                       device cadence (sleep per batch = half the seed
                       loader's measured staging time — a device that
                       consumes input 2x faster than the seed loader
                       can stage it, the input-bound regime this
                       optimization targets). Seed arm: depth-1 double
                       buffer. Streaming arm: deep pipeline
                       (prefetch_depth staged windows).

The headline the PERF_LEDGER bands is `headline/staged_tok_per_s_ratio`
(median across seeds); stall fractions ship alongside. `--full` also
runs the mixed-corpus chaos drill (tools/chaos_train.py --mix=1:
SIGKILL + resume over a sharded+legacy weighted mixture, trajectory
bit-equality) and embeds its verdict, so BENCH_data.json carries the
kill-resume proof next to the throughput claim.

    python tools/data_bench.py --smoke            # tier-1 (seconds)
    python tools/data_bench.py --full --out=BENCH_data.json
"""

import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _parse_args(argv):
    return {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in argv}


def _seed_loader_cls():
    """The pre-streaming reference arm: today's DataLoader with
    `_sample_local` swapped for the SEED implementation (per-crop python
    slice loop + np.stack, single-file memmap) — so the comparison
    isolates the staging path while everything else (rng policy, shapes,
    prefetch bookkeeping) stays shared."""
    import numpy as np

    from avenir_tpu.data.loader import DataLoader, read_wire_format
    from avenir_tpu.utils.faults import get_injector
    from avenir_tpu.utils.retry import call_with_retry

    class SeedDataLoader(DataLoader):
        def _sample_local(self, split):
            path = os.path.join(self.data_dir, f"{split}.bin")
            n = self.grad_accum * self.local_batch
            ix = None

            def read():
                nonlocal ix
                get_injector().fail("data_read_fail", what=f"{split}.bin")
                dtype, offset = read_wire_format(path)
                arr = np.memmap(path, dtype=dtype, mode="r", offset=offset)
                if ix is None:
                    ix = self.rng.integers(0, len(arr) - self.block_size,
                                           size=n)
                x = np.stack([arr[i:i + self.block_size] for i in ix])
                y = np.stack([arr[i + 1:i + 1 + self.block_size]
                              for i in ix])
                return x, y

            x, y = call_with_retry(read, what=f"data read {split}.bin")
            self._stats_fifo.append((split, None))
            return self._shape(x, y)

    return SeedDataLoader


def _build_corpus(tmp, *, n_tokens, shard_tokens, seed=0):
    """One synthetic token stream, both layouts: train.bin (seed arm)
    and train.shards/ (streaming arm) hold identical tokens."""
    import numpy as np

    from avenir_tpu.data.streaming import write_token_shards

    rng = np.random.default_rng(seed)
    toks = rng.integers(0, 50304, n_tokens, dtype=np.uint16)
    legacy = os.path.join(tmp, "legacy")
    sharded = os.path.join(tmp, "sharded")
    os.makedirs(legacy)
    os.makedirs(sharded)
    toks.tofile(os.path.join(legacy, "train.bin"))
    write_token_shards(os.path.join(sharded, "train.shards"), toks,
                       shard_tokens=shard_tokens)
    return legacy, sharded


def _staging_tok_per_s(loader, *, batches, repeats=3):
    """Raw assembly throughput: x-tokens/s of `batches` back-to-back
    _sample_local calls (one warmup call excluded — page-cache warm is
    the steady state both arms run in). Best of `repeats` passes: the
    least-interfered pass is the measurement on a shared host (the
    min-time discipline bench.py documents for --timing=min)."""
    import numpy as np

    x, _ = loader._sample_local("train")
    per_batch = int(np.prod(x.shape))
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(batches):
            loader._sample_local("train")
        best = min(best, time.perf_counter() - t0)
    return batches * per_batch / best, best / batches


def _stall_fraction(loader, *, windows, k, step_s):
    """Consume `windows` windows of `k` batches, sleeping step_s per
    batch between pops (the simulated device window). Returns
    (stall_fraction, staged_x_tokens): stall = time blocked inside
    get_batch_window over total wall."""
    import numpy as np

    blocked = 0.0
    tokens = 0
    t_start = time.perf_counter()
    for _ in range(windows):
        t0 = time.perf_counter()
        x, _ = loader.get_batch_window("train", k)
        blocked += time.perf_counter() - t0
        tokens += int(np.prod(x.shape[:-1])) * x.shape[-1]
        time.sleep(step_s * k)
    wall = time.perf_counter() - t_start
    loader.close()
    return blocked / wall, tokens


def _one_seed(seed, shape, SeedDataLoader):
    from avenir_tpu.data.loader import DataLoader
    from avenir_tpu.obs.metrics import reset_registry

    tmp = tempfile.mkdtemp(prefix="avenir-databench-")
    try:
        legacy, sharded = _build_corpus(
            tmp, n_tokens=shape["n_tokens"],
            shard_tokens=shape["shard_tokens"], seed=seed)
        kw = dict(block_size=shape["block"], batch_size=shape["batch"],
                  grad_accum=1, seed=seed)

        reset_registry()
        old_tps, old_batch_s = _staging_tok_per_s(
            SeedDataLoader(legacy, **kw), batches=shape["batches"])
        new_tps, _ = _staging_tok_per_s(
            DataLoader(sharded, **kw), batches=shape["batches"])

        # cadence: a device that eats 2x faster than the seed loader
        # stages — the regime where the input pipeline is the bottleneck
        step_s = old_batch_s / 2
        old_stall, _ = _stall_fraction(
            SeedDataLoader(legacy, **kw),
            windows=shape["windows"], k=shape["k"], step_s=step_s)
        new_stall, _ = _stall_fraction(
            DataLoader(sharded, prefetch_depth=shape["depth"], **kw),
            windows=shape["windows"], k=shape["k"], step_s=step_s)
        reset_registry()
        return {
            "seed": seed,
            "staged_tok_per_s": {"seed_loader": round(old_tps),
                                 "streaming": round(new_tps)},
            "ratio": round(new_tps / old_tps, 3),
            "sim_step_ms": round(step_s * 1e3, 3),
            "stall_frac": {"seed_loader": round(old_stall, 4),
                           "streaming": round(new_stall, 4)},
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _chaos_mixed(workdir):
    """The kill-resume proof over a sharded+legacy weighted mixture with
    deep prefetch: run tools/chaos_train.py --mix=1 and return its
    verdict (bit_identical is the claim BENCH_data.json commits to)."""
    import subprocess

    out = os.path.join(workdir, "chaos_mix.json")
    cmd = [sys.executable, os.path.join(REPO, "tools", "chaos_train.py"),
           "--mix=1", "--kills=4", "--max_iters=16", "--eval_interval=4",
           f"--out={out}", f"--workdir={os.path.join(workdir, 'chaos')}"]
    r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                       timeout=1800,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, (
        f"mixed-corpus chaos drill failed:\n{r.stdout[-3000:]}\n"
        f"{r.stderr[-3000:]}")
    rep = json.load(open(out))
    return {
        "harness": "chaos_train --mix=1 --kills=4",
        "bit_identical": rep["bit_identical"],
        "iters_compared": rep["iters_compared"],
        "kills": len(rep["kills"]),
        "restores": len(rep.get("restores", [])),
        "data_mix": rep["config"].get("mix", True) and "owt:0.65,code:0.35",
        "prefetch_depth": rep["config"].get("prefetch_depth"),
        "wall_s": rep.get("wall_s"),
    }


def main(argv):
    a = _parse_args(argv)
    smoke = "smoke" in a
    # full shape = one pod host's real staging load: 64 sequences per
    # host batch (8 devices x 8), 1M-token shards (tiny shards make the
    # per-shard open/gather overhead the bottleneck — re-shard coarser)
    shape = (dict(n_tokens=200_000, shard_tokens=65_536, block=128,
                  batch=8, batches=8, windows=3, k=2, depth=3)
             if smoke else
             dict(n_tokens=16_000_000, shard_tokens=1 << 20, block=1024,
                  batch=64, batches=32, windows=10, k=8, depth=4))
    seeds = [0] if smoke else [0, 1, 2]
    SeedDataLoader = _seed_loader_cls()

    results = [_one_seed(s, shape, SeedDataLoader) for s in seeds]
    ratios = sorted(r["ratio"] for r in results)
    med_ratio = ratios[len(ratios) // 2]
    spread = ((ratios[-1] - ratios[0]) / med_ratio) if med_ratio else 1.0
    old_stalls = [r["stall_frac"]["seed_loader"] for r in results]
    new_stalls = [r["stall_frac"]["streaming"] for r in results]
    med = lambda v: sorted(v)[len(v) // 2]  # noqa: E731

    report = {
        "tool": "data_bench", "smoke": smoke,
        "config": {**shape, "seeds": seeds,
                   "cadence": "sim step = seed-loader staging time / 2"},
        "headline": {
            "staged_tok_per_s_ratio": med_ratio,
            "stall_frac_seed_loader": med(old_stalls),
            "stall_frac_streaming": med(new_stalls),
            "ratio_spread_frac": round(spread, 4),
        },
        "seeds": results,
        "ok": True,
    }
    # acceptance (ISSUE 19): >=1.3x staged tokens/s OR <= half the
    # input-stall fraction; the committed full artifact must hold it
    meets = (med_ratio >= 1.3
             or med(new_stalls) <= med(old_stalls) / 2)
    if not smoke:
        report["ok"] &= meets
        report["headline"]["meets_acceptance"] = meets
        workdir = tempfile.mkdtemp(prefix="avenir-databench-chaos-")
        try:
            report["resume"] = _chaos_mixed(workdir)
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        report["ok"] &= bool(report["resume"]["bit_identical"])
    else:
        # the smoke's job is exercising both arms end to end, not
        # hitting the perf bar on a noisy shared CI host
        report["headline"]["meets_acceptance"] = meets

    line = json.dumps(report, indent=1)
    print(line)
    if a.get("out"):
        with open(a["out"], "w") as f:
            f.write(line + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
