"""Offline xprof breakdown of the GPT-2-124M train step (dev tool).

Captures a jax.profiler trace of a few steps on the real chip and prints
the op-profile category table (per-category time + FLOP utilization) plus
the top individual ops — the tool that found the erf-GELU tax in round 2.

Usage: python tools/xprof_step.py [--batch=16] [--top=25]
"""

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def capture(step_fn_builder, outdir, n_steps=6):
    fn, args = step_fn_builder()
    # warmup/compile outside the trace
    out = fn(*args)
    float(jax.tree.leaves(out[-1] if isinstance(out, tuple) else out)[0].ravel()[0])
    jax.profiler.start_trace(outdir)
    for _ in range(n_steps):
        out = fn(*args)
    float(jax.tree.leaves(out[-1] if isinstance(out, tuple) else out)[0].ravel()[0])
    jax.profiler.stop_trace()


def build_step(B, T):
    import jax.numpy as jnp
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_train_step, make_step_fns

    C, H, V, L = 768, 12, 50304, 12
    rng = np.random.default_rng(0)
    x_tok = jnp.asarray(rng.integers(0, V, (1, B, T)).astype(np.int32))
    y_tok = jnp.asarray(rng.integers(0, V, (1, B, T)).astype(np.int32))
    cfg = GPTConfig(block_size=T, vocab_size=V, n_layer=L, n_head=H,
                    n_embd=C, dropout=0.0, bias=True,
                    compute_dtype="bfloat16", attn_impl="pallas")
    model = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)
    tx, _ = make_optimizer(params, learning_rate=6e-4, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=10, lr_decay_iters=1000, min_lr=6e-5)
    opt_state = jax.jit(tx.init)(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_train_step(step_fn, tx)
    key = jax.random.key(0)

    state = {"p": params, "o": opt_state}

    def run(_):
        state["p"], state["o"], m = step(state["p"], state["o"], key,
                                         x_tok, y_tok)
        return m["loss"]

    return (lambda: (run, (0,)))


def analyze(outdir, top=25):
    from xprof.convert import raw_to_tool_data as rtd

    xspaces = glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                        recursive=True)
    assert xspaces, f"no xplane under {outdir}"
    sess = os.path.dirname(xspaces[0])
    params = {"tqx": "", "host": "", "module_name": ""}
    data, _ = rtd.xspace_to_tool_data([xspaces[0]], "op_profile", params)
    import json

    prof = json.loads(data) if isinstance(data, (str, bytes)) else data
    node = prof.get("byProgramExcludeIdle") or prof.get("byProgram")

    def total_time(n):
        return float(n.get("metrics", {}).get("rawTime", 0.0))

    rows = []

    def walk_categories(n, depth=0):
        for ch in n.get("children", []):
            nm = ch.get("name", "?")
            t = total_time(ch)
            flops = ch.get("metrics", {}).get("flops", 0.0)
            rows.append((t, nm, flops, depth))
            if depth < 1:
                walk_categories(ch, depth + 1)

    walk_categories(node)
    tot = total_time(node)
    print(f"total rawTime: {tot/1e9:.3f} ms (over traced steps)")
    rows.sort(key=lambda r: -r[0])
    shown = 0
    for t, nm, fl, depth in rows:
        if shown >= top:
            break
        pad = "  " * depth
        print(f"{pad}{t/1e9:9.3f} ms  {100*t/tot:5.1f}%  flops-util={fl:5.1f}"
              f"  {nm[:90]}")
        shown += 1


if __name__ == "__main__":
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    B = int(args.get("batch", 16))
    T = int(args.get("block", 1024))
    top = int(args.get("top", 25))
    outdir = args.get("out", "/tmp/xprof_step")
    os.system(f"rm -rf {outdir}")
    capture(build_step(B, T), outdir)
    analyze(outdir, top=top)
