"""Fleet cache telescope report + bench (ISSUE 16).

Drives an in-process paged Router with the cache telescope and the
flight recorder armed over a seeded MULTI-TENANT workload — T tenants,
each with its own shared system prefix, tails random per request — and
renders what the telescope saw:

- the fleet cache map (per-replica advertised chains + staleness),
- the hottest shared chains fleet-wide,
- the dispatch token partition (reused / missed / cold) with the
  estimated prefill ms the fleet left on the table, and
- a per-tenant missed-reuse breakdown from the `missed_reuse` trace
  events (which tenant's prefixes the cache-blind placement scatters).

The default run stays AFFINITY-BLIND — placement maximizes free-slot
fraction, ignoring cache content — so a tenant's requests land on
whichever replica has room and the fleet re-prefills prefixes it
already holds. That cost is the bench headline:

    missed_reuse_frac = prefix_tokens_missed / all dispatched tokens

written to BENCH_cache_obs.json over three seeds and banded in
PERF_LEDGER.json as the BASELINE (the affinity band itself rides
BENCH_kv_cdn.json, tools/serve_bench.py --sweep --kv_cdn).

`--affinity` (ISSUE 17) re-runs the same workload with the KV CDN
armed (Router(affinity=True): prefix-affinity placement + peer pulls)
and renders the affinity-effectiveness section — the prefix-hit depth
histogram, the pull ledger (src->dst, pages, outcome), and the
residual missed_reuse partition that remains AFTER affinity routing.
`--smoke` runs blind + affinity back to back and asserts affinity
strictly reduces the missed fraction — the tier-1 tripwire a silent
affinity regression cannot ship past.

    python tools/cache_report.py                  # bench, writes JSON
    python tools/cache_report.py --smoke          # tier-1 CI path
    python tools/cache_report.py --affinity       # KV CDN effectiveness
    python tools/cache_report.py --seed=1 --n_requests=96
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

import numpy as np  # noqa: E402


def _mk_workload(rng, V, *, n_tenants, prefix_len, n_requests,
                 tail_lo, tail_hi):
    """T tenants x one shared system prefix each + per-request random
    tails: the prefix-cache-friendly shape (agents, RAG preambles)
    where placement affinity matters most."""
    prefixes = [[int(t) for t in rng.integers(0, V, prefix_len)]
                for _ in range(n_tenants)]
    reqs = []
    for _ in range(n_requests):
        tenant = int(rng.integers(0, n_tenants))
        tail = [int(t) for t in
                rng.integers(0, V, int(rng.integers(tail_lo, tail_hi + 1)))]
        reqs.append((tenant, prefixes[tenant] + tail))
    return prefixes, reqs


def _run_telescope(seed, *, n_replicas, n_slots, n_tenants, prefix_len,
                   tail_lo, tail_hi, n_requests, n_conc, max_new,
                   page_size, n_pages, prefill_chunk, block_size,
                   vocab_size=256, n_layer=1, n_embd=32, affinity=False):
    """One seeded run — affinity-blind by default, the KV CDN armed
    with `affinity=True` (ISSUE 17) — returning the telescope's full
    accounting (counters, per-tenant misses, map view, hit-depth
    histogram, pull ledger) plus enough to assert the partition
    identity exactly."""
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.obs.trace import Tracer
    from avenir_tpu.serve import Router

    model = GPT(GPTConfig(
        block_size=block_size, vocab_size=vocab_size, n_layer=n_layer,
        n_head=2, n_embd=n_embd, dropout=0.0, bias=True,
        attn_impl="xla"), rngs=nnx.Rngs(seed))
    reg = MetricsRegistry()
    tracer = Tracer(registry=reg, capacity=16384)
    router = Router(
        model, n_replicas=n_replicas, n_slots=n_slots,
        max_seq_len=block_size, registry=reg, seed=seed,
        tracer=tracer, cache_telescope=True, affinity=affinity,
        engine_kwargs={"kv_impl": "paged", "page_size": page_size,
                       "n_pages": n_pages,
                       "prefill_chunk": prefill_chunk})
    rng = np.random.default_rng(seed)
    _, reqs = _mk_workload(
        rng, vocab_size, n_tenants=n_tenants, prefix_len=prefix_len,
        n_requests=n_requests, tail_lo=tail_lo, tail_hi=tail_hi)
    tenant_of = {}
    dispatched_tokens = 0
    submitted, done = 0, []
    while len(done) < n_requests:
        while submitted < n_requests and submitted - len(done) < n_conc:
            tenant, prompt = reqs[submitted]
            rid = router.submit(prompt, max_new_tokens=max_new,
                                temperature=1.0, top_k=None)
            tenant_of[rid] = tenant
            dispatched_tokens += len(prompt)
            submitted += 1
        done.extend(router.step())
    router.drain()
    snap = reg.snapshot()
    counters = snap["counters"]
    reused = counters.get("prefix_tokens_reused", 0.0)
    missed = counters.get("prefix_tokens_missed", 0.0)
    cold = counters.get("prefix_tokens_cold", 0.0)
    total = reused + missed + cold
    by_tenant = {}
    est_saved_ms = 0.0
    hit_hist = {}   # shared-prefix depth (tokens) -> prefix_hit count
    pulls = []      # the pull ledger (ISSUE 17): one row per broker
    for e in tracer.events():
        if e["ev"] == "prefix_hit":
            d = int(e["shared_tokens"])
            hit_hist[d] = hit_hist.get(d, 0) + 1
            continue
        if e["ev"] == "prefix_pull":
            pulls.append({"src": e["src"], "dst": e["dst"],
                          "pages": int(e["pages"]),
                          "depth": int(e["depth"]),
                          "outcome": e["outcome"]})
            continue
        if e["ev"] != "missed_reuse":
            continue
        t = tenant_of.get(e["rid"])
        if t is not None:
            agg = by_tenant.setdefault(t, {"events": 0, "missed": 0})
            agg["events"] += 1
            agg["missed"] += e["missed"]
        est_saved_ms += e.get("est_ms_saved", 0.0)
    cmap = router._cache_map
    map_view = {
        str(rid): {
            "chains": len(cmap.nodes(rid)),
            "deepest_tok": max(
                (int(n[0]) for n in cmap.nodes(rid).values()),
                default=0),
        }
        for rid in cmap.replicas()
    }
    # hottest advertised chains fleet-wide: (hits, n_tokens) desc
    chains = []
    for rid in cmap.replicas():
        for dig, n in cmap.nodes(rid).items():
            chains.append((int(n[3]), int(n[0]), str(rid), dig))
    chains.sort(reverse=True)
    router.close()
    assert len(done) == n_requests
    assert all(f.finish_reason == "length" for f in done), (
        [f.finish_reason for f in done])
    return {
        "seed": seed,
        "affinity": bool(affinity),
        "n_served": len(done),
        "dispatched_tokens": dispatched_tokens,
        "reused": reused, "missed": missed, "cold": cold,
        "audited_tokens": total,
        "missed_reuse_frac": missed / total if total else 0.0,
        "reused_frac": reused / total if total else 0.0,
        "est_prefill_ms_saved": est_saved_ms,
        "prefill_ms": counters.get("serve_prefill_ms", 0.0),
        "by_tenant": by_tenant,
        "map": map_view,
        "top_chains": chains[:8],
        "hit_depth_hist": hit_hist,
        "pulls": pulls,
        "affinity_hits": counters.get("affinity_hits", 0.0),
        "pull_pages": counters.get("prefix_pull_pages", 0.0),
        "pull_bytes": counters.get("prefix_pull_bytes", 0.0),
        "pull_fallbacks": counters.get("prefix_pull_fallbacks", 0.0),
    }


def _print_run(r):
    print(f"[cache] seed {r['seed']}: {r['n_served']} served, "
          f"{r['audited_tokens']:.0f} prompt tokens audited")
    print(f"  partition: reused {r['reused']:.0f}  "
          f"missed {r['missed']:.0f}  cold {r['cold']:.0f}  "
          f"(missed frac {r['missed_reuse_frac']:.1%})")
    print(f"  est prefill ms left on the table: "
          f"{r['est_prefill_ms_saved']:.1f} "
          f"(of {r['prefill_ms']:.1f} ms spent)")
    print("  fleet map: " + "   ".join(
        f"r{rid}: {v['chains']} chains, deepest {v['deepest_tok']} tok"
        for rid, v in sorted(r["map"].items())))
    if r["top_chains"]:
        print("  hottest chains: " + "   ".join(
            f"{dig[:8]}@r{rid} {n}tok x{h}"
            for h, n, rid, dig in r["top_chains"][:4]))
    for t, agg in sorted(r["by_tenant"].items()):
        print(f"  tenant {t}: {agg['events']} missed-reuse dispatches, "
              f"{agg['missed']} tokens recomputed elsewhere")


def _print_affinity(r):
    """The affinity-effectiveness section (ISSUE 17): what the KV CDN
    actually bought — hit depths, the pull ledger, and the residual
    missed_reuse partition affinity routing could not reclaim."""
    print(f"[cache] affinity effectiveness (seed {r['seed']}):")
    print(f"  affinity hits: {r['affinity_hits']:.0f} of "
          f"{r['n_served']} dispatches")
    if r["hit_depth_hist"]:
        rows = sorted(r["hit_depth_hist"].items())
        print("  hit depth histogram: " + "   ".join(
            f"{d}tok x{c}" for d, c in rows))
    if r["pulls"]:
        ok = [p for p in r["pulls"] if p["outcome"] == "ok"]
        print(f"  pull ledger: {len(ok)}/{len(r['pulls'])} ok, "
              f"{r['pull_pages']:.0f} pages / "
              f"{r['pull_bytes'] / 1024:.0f} KiB shipped, "
              f"{r['pull_fallbacks']:.0f} fallbacks")
        for p in r["pulls"][:8]:
            print(f"    r{p['src']} -> r{p['dst']}: {p['pages']} pages "
                  f"(depth {p['depth']} tok, {p['outcome']})")
    else:
        print("  pull ledger: no pulls brokered")
    print(f"  residual partition: reused {r['reused']:.0f}  "
          f"missed {r['missed']:.0f}  cold {r['cold']:.0f}  "
          f"(residual missed frac {r['missed_reuse_frac']:.1%})")


def cache_report(args):
    """Entry point (dict args — tests call this directly). `--smoke`
    asserts the mechanics at tiny scale; the default bench runs three
    seeds and writes BENCH_cache_obs.json."""
    import json as _json

    smoke = "smoke" in args
    cfg = dict(
        n_replicas=int(args.get("n_replicas", 2 if smoke else 3)),
        n_slots=int(args.get("n_slots", 2)),
        n_tenants=int(args.get("n_tenants", 2 if smoke else 4)),
        prefix_len=int(args.get("prefix_len", 24 if smoke else 48)),
        tail_lo=int(args.get("tail_lo", 4)),
        tail_hi=int(args.get("tail_hi", 8 if smoke else 16)),
        n_requests=int(args.get("n_requests", 10 if smoke else 48)),
        n_conc=int(args.get("n_conc", 4 if smoke else 6)),
        max_new=int(args.get("max_new_tokens", 4 if smoke else 8)),
        page_size=int(args.get("page_size", 8)),
        n_pages=int(args.get("n_pages", 96 if smoke else 192)),
        prefill_chunk=int(args.get("prefill_chunk", 16)),
        block_size=int(args.get("block_size", 64 if smoke else 128)),
    )
    if smoke:
        seed = int(args.get("seed", 0))
        r = _run_telescope(seed, **cfg)
        _print_run(r)
        # the partition identity: every dispatched prompt token landed
        # in exactly one bucket (no failovers here, so dispatches ==
        # submissions)
        assert r["audited_tokens"] == r["dispatched_tokens"], (
            r["audited_tokens"], r["dispatched_tokens"])
        # affinity-blind placement over shared-prefix tenants MUST
        # leave reuse on the table across >= 2 replicas — a zero here
        # means the telescope went blind, not that routing got smart
        assert r["missed"] > 0, "no missed reuse observed in smoke"
        assert r["reused"] > 0, "no local reuse observed in smoke"
        # the affinity tripwire (ISSUE 17): same workload, KV CDN on —
        # a silent affinity regression cannot leave this green
        a = _run_telescope(seed, affinity=True, **cfg)
        _print_affinity(a)
        assert a["audited_tokens"] == a["dispatched_tokens"], (
            a["audited_tokens"], a["dispatched_tokens"])
        assert a["affinity_hits"] > 0, "affinity never placed on cache"
        assert a["missed_reuse_frac"] < r["missed_reuse_frac"], (
            "affinity routing did not reduce missed reuse: "
            f"{a['missed_reuse_frac']:.3f} vs blind "
            f"{r['missed_reuse_frac']:.3f}")
        print("[cache] smoke ok: partition exact, misses visible, "
              f"affinity cuts missed frac {r['missed_reuse_frac']:.1%} "
              f"-> {a['missed_reuse_frac']:.1%}")
        return 0
    if "affinity" in args:
        r = _run_telescope(int(args.get("seed", 0)), affinity=True,
                           **cfg)
        _print_run(r)
        _print_affinity(r)
        return 0
    seeds = [int(s) for s in str(args.get("seeds", "0,1,2")).split(",")]
    runs = [_run_telescope(s, **cfg) for s in seeds]
    for r in runs:
        _print_run(r)
    fracs = [r["missed_reuse_frac"] for r in runs]
    mean = sum(fracs) / len(fracs)
    spread = (max(fracs) - min(fracs)) / mean if mean else 0.0
    bench = {
        "kind": "cache_obs",
        "config": cfg,
        "seeds": [
            {"seed": r["seed"],
             "missed_reuse_frac": r["missed_reuse_frac"],
             "reused_frac": r["reused_frac"],
             "audited_tokens": r["audited_tokens"],
             "est_prefill_ms_saved": r["est_prefill_ms_saved"]}
            for r in runs],
        "missed_reuse_frac": mean,
        "seed_spread_frac": spread,
        "note": ("missed-reuse fraction of dispatched prompt tokens "
                 "under AFFINITY-BLIND routing — the baseline the "
                 "PR 17 cache-affinity router must beat (direction: "
                 "lower). Partition identity asserted per seed."),
        "ok": bool(
            all(r["audited_tokens"] == r["dispatched_tokens"]
                for r in runs)
            and all(f > 0.0 for f in fracs)),
    }
    out_path = args.get("out", "BENCH_cache_obs.json")
    with open(out_path, "w") as f:
        _json.dump(bench, f, indent=1)
    print(f"[cache] missed_reuse_frac {mean:.3f} over seeds "
          f"{seeds} (spread {spread:.2f}) -> {out_path} "
          f"(ok={bench['ok']})")
    return 0 if bench["ok"] else 1


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    return cache_report(args)


if __name__ == "__main__":
    sys.exit(main())
