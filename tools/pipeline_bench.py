"""Pipeline-schedule microbench (1f1b satellite, sibling of
tools/loss_tail_bench.py): per-schedule ms/step + compiled temp-memory
bytes for `gpipe` / `remat` / `1f1b` at pipe:2 and pipe:4, M = 2p and
4p. The numbers land in BASELINE.md "Pipeline cost table".

Each (schedule, p, M) cell runs in its OWN subprocess: PJRT's
`peak_bytes_in_use` is a process-lifetime high-water mark (same reason
loss_tail_bench forks), and the forced host-device count is baked into
XLA_FLAGS at interpreter start. The child jits `grad(loss)` of the
scan-stacked GPT over a `pipe:p` mesh and reports XLA's
`memory_analysis().temp_size_in_bytes` — the compiled fwd+bwd scratch,
which is where the schedules differ — plus wall ms/step.

gpipe/remat run the `blocked` loss tail (their production class since
the fused-CE PR) so the A/B isolates the SCHEDULE; 1f1b's tail is
always blocked-inside-the-region by construction. A cell that fails to
compile (OOM on a real chip) records the error and moves on — "M=4p
does not fit under gpipe but does under 1f1b" is a result, not a
failure.

    python tools/pipeline_bench.py                  # full grid, one JSON line
    python tools/pipeline_bench.py --steps=5 --vocab=8192
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# default shape: deep enough for the stash classes to separate (L=8),
# realistic-vocab tail (the per-micro in-region tail is Bm-sized, the
# outside tails are B-sized — at tiny vocabs that structural win would
# be invisible), small enough that 12 CPU-harness compiles stay quick
SHAPE = dict(batch=16, block=128, n_embd=128, n_head=4, n_layer=8,
             vocab=8192)


def _parse_args():
    return {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}


def _measure_one(schedule, p, M, dims, steps):
    import jax
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.utils.benching import median_low, peak_hbm_bytes

    cfg = GPTConfig(
        block_size=dims["block"], vocab_size=dims["vocab"],
        n_layer=dims["n_layer"], n_head=dims["n_head"],
        n_embd=dims["n_embd"], dropout=0.0, bias=False, attn_impl="xla",
        scan_layers=True, pipeline_microbatches=M,
        pipeline_schedule=schedule,
        loss_impl="" if schedule == "1f1b" else "blocked",
    )
    mesh = make_mesh(f"pipe:{p}")
    with jax.set_mesh(mesh):
        graphdef, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)
        B = dims["batch"]
        x = jax.random.randint(jax.random.key(1), (B, dims["block"]), 0,
                               dims["vocab"])
        y = jax.random.randint(jax.random.key(2), (B, dims["block"]), 0,
                               dims["vocab"])

        def loss_fn(params):
            _, loss = nnx.merge(graphdef, params)(x, targets=y)
            return loss

        try:
            comp = jax.jit(jax.grad(loss_fn)).lower(params).compile()
            temp = comp.memory_analysis().temp_size_in_bytes
            g = comp(params)
            jax.block_until_ready(g)
        except Exception as e:  # OOM at this cell: record and move on
            return {"error": str(e).splitlines()[0][:200]}
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            g = comp(params)
            jax.block_until_ready(g)
            times.append((time.perf_counter() - t0) * 1e3)
    return {
        "ms_per_step": round(median_low(times), 3),
        "temp_bytes": int(temp),
        "peak_hbm_bytes": peak_hbm_bytes(),
    }


def _child(extra_args, n_devices):
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + extra_args,
        capture_output=True, text=True, env=env,
    )
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return {"error": (out.stderr or "no output")
                .strip().splitlines()[-1][:200]}


def main():
    args = _parse_args()
    if "cell" in args:
        # child mode: one (schedule, p, M) cell
        from avenir_tpu.platform import honor_jax_platforms_env

        honor_jax_platforms_env()
        sched, p, M = args["cell"].split(":")
        dims = json.loads(args["dims"])
        print(json.dumps(_measure_one(sched, int(p), int(M), dims,
                                      int(args["steps"]))))
        return

    dims = dict(SHAPE)
    for k in ("batch", "block", "vocab"):
        if k in args:
            dims[k] = int(args[k])
    steps = int(args.get("steps", 3))
    pipes = [int(v) for v in args.get("pipes", "2,4").split(",")]
    schedules = args.get("schedules", "gpipe,remat,1f1b").split(",")

    results = {}
    for p in pipes:
        for M in (2 * p, 4 * p):
            for sched in schedules:
                key = f"{sched}/pipe{p}/M{M}"
                results[key] = _child(
                    [f"--cell={sched}:{p}:{M}",
                     f"--dims={json.dumps(dims)}", f"--steps={steps}"],
                    n_devices=p,
                )

    print(json.dumps({
        "metric": "pipeline_schedule_fwd_bwd",
        "unit": "ms/step + temp bytes",
        "shape": dims,
        "steps": steps,
        "results": results,
    }))


if __name__ == "__main__":
    main()
