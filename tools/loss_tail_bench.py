"""Loss-tail microbench (ISSUE 3 satellite): reference vs blocked vs
pallas fused cross-entropy at real vocab shapes.

Times a jitted value_and_grad of the bare tail — loss(x @ W) plus dx/dW —
so the A/B isolates exactly the bytes the fused tail removes. Each impl
runs in its OWN subprocess: PJRT's `peak_bytes_in_use` is a
process-lifetime high-water mark that never resets, so measuring two
impls in one process would report the first impl's (largest) peak for
all of them and hide the exact memory win this tool exists to show.
The peak field is None-tolerant on CPU, like bench.py's.

    python tools/loss_tail_bench.py --shape=gpt2             # on TPU
    python tools/loss_tail_bench.py --shape=tiny --steps=3   # anywhere

Shapes: gpt2 (B16 T1024 C768 V50304), llama (B8 T1024 C4096 V128256),
tiny (CPU smoke). Prints ONE JSON line like serve_bench/bench.py.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from avenir_tpu.platform import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

SHAPES = {
    "gpt2": dict(batch=16, block=1024, n_embd=768, vocab=50304),
    "llama": dict(batch=8, block=1024, n_embd=4096, vocab=128256),
    "tiny": dict(batch=2, block=128, n_embd=64, vocab=512),
}


def _parse_args():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    return args


def _measure_one(impl, dims, steps, on_tpu):
    """Run ONE impl in this process and return its result dict — the
    process boundary is what makes peak_hbm_bytes per-impl truthful."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from avenir_tpu.models.common import cross_entropy_loss
    from avenir_tpu.ops.fused_ce import fused_cross_entropy
    from avenir_tpu.utils.benching import median_low, peak_hbm_bytes

    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    B, T, C, V = (dims["batch"], dims["block"], dims["n_embd"],
                  dims["vocab"])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, T, C)).astype(np.float32) * 0.02,
                    dtype)
    w = jnp.asarray(rng.normal(size=(C, V)).astype(np.float32) * 0.02, dtype)
    y = jnp.asarray(rng.integers(0, V, (B, T)).astype(np.int32))

    if impl == "reference":
        loss_fn = lambda x, w: cross_entropy_loss(
            jnp.einsum("btc,cv->btv", x, w), y)
    else:
        loss_fn = lambda x, w: fused_cross_entropy(
            x, w, y, impl=impl, w_layout="cv")

    step = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))
    try:
        l, (dx, dw) = step(x, w)  # trace + compile + warmup
        float(l)
    except Exception as e:  # OOM at this shape: record and move on
        return {"error": str(e).splitlines()[0][:200]}
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        l, (dx, dw) = step(x, w)
        float(l)  # D2H fence (the reliable fence on tunneled hosts)
        times.append((time.perf_counter() - t0) * 1e3)
    return {
        "ms_per_step": round(median_low(times), 3),
        "loss": round(float(l), 5),
        "peak_hbm_bytes": peak_hbm_bytes(),
    }


def _child(extra_args):
    """Spawn this file as a child process and parse its one-line JSON."""
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__)] + extra_args,
        capture_output=True, text=True,
    )
    try:
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (json.JSONDecodeError, IndexError):
        return {"error": (out.stderr or "no output")
                .strip().splitlines()[-1][:200]}


def main():
    args = _parse_args()
    if "impl" in args:
        # child mode: measure one impl, print its JSON fragment
        import jax

        on_tpu = jax.default_backend() == "tpu"
        dims = json.loads(args["dims"])
        print(json.dumps(_measure_one(args["impl"], dims,
                                      int(args["steps"]), on_tpu)))
        return
    if "probe" in args:
        # child mode: report the platform without doing any work
        import jax

        print(json.dumps({"backend": jax.default_backend(),
                          "device": str(jax.devices()[0].device_kind)}))
        return

    # The PARENT must never initialize a jax backend: on TPU the libtpu
    # client is process-exclusive, and a parent holding it would lock
    # every measurement child out of the chip. Probe via a subprocess.
    probe = _child(["--probe"])
    on_tpu = probe.get("backend") == "tpu"
    shape = args.get("shape", "gpt2" if on_tpu else "tiny")
    assert shape in SHAPES, f"--shape must be one of {sorted(SHAPES)}"
    dims = dict(SHAPES[shape])
    dims["batch"] = int(args.get("batch", dims["batch"]))
    dims["block"] = int(args.get("block", dims["block"]))
    steps = int(args.get("steps", 20 if on_tpu else 3))
    impls = args.get("impls", "reference,blocked,pallas").split(",")

    results = {
        impl: _child([f"--impl={impl}", f"--dims={json.dumps(dims)}",
                      f"--steps={steps}"])
        for impl in impls
    }

    print(json.dumps({
        "metric": "loss_tail_fwd_bwd_ms",
        "unit": "ms/step",
        "shape": {**dims, "dtype": "bfloat16" if on_tpu else "float32"},
        "device": probe.get("device", "unknown"),
        "steps": steps,
        "results": results,
    }))


if __name__ == "__main__":
    main()
