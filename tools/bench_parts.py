"""Component-level timing of the GPT-2-124M train step on the real chip.

Decomposes the step into: full step, trunk-only (no lm_head/CE), lm_head+CE
alone, attention alone (pallas vs xla) — so BASELINE.md perf claims point at
measured numbers, not guesses. Dev tool; not part of the test suite.

Usage: python tools/bench_parts.py [--batch=16] [--block=1024]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, warmup=3, iters=10):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    # D2H readback fences the queue on the axon-tunneled platform
    np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[0]
    return (time.perf_counter() - t0) / iters


def timeit_step_chain(step, opt_state, params, key, xb, yb,
                      warmup=3, iters=10):
    """Time a donated-state train step by chaining it (re-initializing the
    donated buffers each call would skew); scalar loss readback fences."""
    p, o = params, opt_state
    for _ in range(warmup):
        p, o, m = step(p, o, key, xb, yb)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        p, o, m = step(p, o, key, xb, yb)
    float(m["loss"])
    return (time.perf_counter() - t0) / iters


def main():
    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    B = int(args.get("batch", 16))
    T = int(args.get("block", 1024))
    C, H, V, L = 768, 12, 50304, 12

    rng = np.random.default_rng(0)
    x_tok = jnp.asarray(rng.integers(0, V, (B, T)).astype(np.int32))
    y_tok = jnp.asarray(rng.integers(0, V, (B, T)).astype(np.int32))
    xf = jnp.asarray(rng.standard_normal((B, T, C)).astype(np.float32) * 0.02,
                     jnp.bfloat16)
    wte = jnp.asarray(rng.standard_normal((V, C)).astype(np.float32) * 0.02)
    q = jnp.asarray(rng.standard_normal((B, T, H, C // H)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, T, H, C // H)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, T, H, C // H)), jnp.bfloat16)

    results = {}

    # ---- full train step (the bench.py number, minus data movement) ----
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_train_step, make_step_fns

    for attn in ("pallas", "xla"):
        cfg = GPTConfig(block_size=T, vocab_size=V, n_layer=L, n_head=H,
                        n_embd=C, dropout=0.0, bias=True,
                        compute_dtype="bfloat16", attn_impl=attn)
        model = GPT(cfg, rngs=nnx.Rngs(0))
        graphdef, params = nnx.split(model, nnx.Param)
        tx, _ = make_optimizer(params, learning_rate=6e-4, weight_decay=0.1,
                               beta1=0.9, beta2=0.95, grad_clip=1.0,
                               warmup_iters=10, lr_decay_iters=1000,
                               min_lr=6e-5)
        opt_state = jax.jit(tx.init)(params)
        step_fn, _ = make_step_fns(graphdef, dropout=0.0)
        step = jit_train_step(step_fn, tx)
        key = jax.random.key(0)
        xb, yb = x_tok[None], y_tok[None]

        results[f"full_step_{attn}"] = timeit_step_chain(
            step, opt_state, params, key, xb, yb
        )
        del params, opt_state

    # ---- trunk only: fwd+bwd through blocks, NO lm_head/CE ----
    cfg = GPTConfig(block_size=T, vocab_size=V, n_layer=L, n_head=H,
                    n_embd=C, dropout=0.0, bias=True,
                    compute_dtype="bfloat16", attn_impl="pallas")
    model = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)

    def trunk_loss(p, idx):
        m = nnx.merge(graphdef, p)
        pos = jnp.arange(T, dtype=jnp.int32)
        h = m.wte(idx) + m.wpe(pos)[None]
        for blk in m.h:
            h = blk(h)
        h = m.ln_f(h)
        return h.astype(jnp.float32).mean()

    g_trunk = jax.jit(jax.grad(trunk_loss))
    results["trunk_fwd_bwd"] = timeit(lambda: g_trunk(params, x_tok))

    # ---- lm_head + CE alone: grad wrt (x, wte) ----
    from avenir_tpu.models.common import cross_entropy_loss

    def head_loss(xh, w, tgt):
        logits = (xh @ w.astype(xh.dtype).T)
        return cross_entropy_loss(logits, tgt, ignore_index=-1)

    g_head = jax.jit(jax.grad(head_loss, argnums=(0, 1)))
    results["lm_head_ce_fwd_bwd"] = timeit(lambda: g_head(xf, wte, y_tok))

    # ---- attention alone, fwd+bwd ----
    from avenir_tpu.ops import causal_attention

    for impl in ("pallas", "xla"):
        def attn_loss(q_, k_, v_):
            return causal_attention(q_, k_, v_, impl=impl).astype(
                jnp.float32).mean()

        g_attn = jax.jit(jax.grad(attn_loss, argnums=(0, 1, 2)))
        results[f"attn_fwd_bwd_{impl}"] = timeit(lambda: g_attn(q, k, v))
        # x12 layers
        results[f"attn_fwd_bwd_{impl}_x{L}"] = results[f"attn_fwd_bwd_{impl}"] * L

    for name, dt in results.items():
        print(f"{name:32s} {dt * 1e3:8.2f} ms")


def ablations():
    """Step-cost decomposition by ablation (one jit each, real chip)."""
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_train_step, make_step_fns

    args = {a.split("=")[0].lstrip("-"): (a.split("=") + ["1"])[1]
            for a in sys.argv[1:]}
    B = int(args.get("batch", 16))
    T = int(args.get("block", 1024))
    C, H, V, L = 768, 12, 50304, 12
    rng = np.random.default_rng(0)
    x_tok = jnp.asarray(rng.integers(0, V, (B, T)).astype(np.int32))
    y_tok = jnp.asarray(rng.integers(0, V, (B, T)).astype(np.int32))

    cfg = GPTConfig(block_size=T, vocab_size=V, n_layer=L, n_head=H,
                    n_embd=C, dropout=0.0, bias=True,
                    compute_dtype="bfloat16", attn_impl="pallas")
    model = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)

    def timed_grad(loss_fn, name):
        g = jax.jit(jax.grad(loss_fn))
        dt = timeit(lambda: g(params))
        print(f"{name:44s} {dt * 1e3:8.2f} ms")

    def full_loss(p):
        m = nnx.merge(graphdef, p)
        _, loss = m(x_tok, y_tok)
        return loss

    def mean_logit_loss(p):  # lm_head matmul kept, CE dropped
        m = nnx.merge(graphdef, p)
        pos = jnp.arange(T, dtype=jnp.int32)
        h = m.wte(x_tok) + m.wpe(pos)[None]
        for blk in m.h:
            h = blk(h)
        h = m.ln_f(h).astype(jnp.bfloat16)
        lg = m.wte.attend(h)
        return lg.astype(jnp.float32).mean()

    def trunk_loss(p):  # no lm_head at all
        m = nnx.merge(graphdef, p)
        pos = jnp.arange(T, dtype=jnp.int32)
        h = m.wte(x_tok) + m.wpe(pos)[None]
        for blk in m.h:
            h = blk(h)
        return m.ln_f(h).astype(jnp.float32).mean()

    timed_grad(full_loss, "grad: full (trunk+lm_head+CE)")
    timed_grad(mean_logit_loss, "grad: trunk+lm_head, mean loss (no CE)")
    timed_grad(trunk_loss, "grad: trunk only")

    # optimizer cost: full step minus grad-only
    tx, _ = make_optimizer(params, learning_rate=6e-4, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=10, lr_decay_iters=1000, min_lr=6e-5)
    opt_state = jax.jit(tx.init)(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_train_step(step_fn, tx)
    key = jax.random.key(0)
    xb, yb = x_tok[None], y_tok[None]
    dt = timeit_step_chain(step, opt_state, params, key, xb, yb)
    print(f"{'full train step (grad+clip+adamw)':44s} {dt * 1e3:8.2f} ms")


if __name__ == "__main__":
    if "--ablate" in sys.argv:
        ablations()
    else:
        main()
