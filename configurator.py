"""Globals-override configurator (SURVEY.md §2a R3).

The nanoGPT-lineage config pattern: a script declares its defaults as module
globals, then calls `configure(globals())`, which

  1. if the first positional CLI arg is a path, `exec`s that config file
     into the globals (so config files are plain Python assigning the same
     names), and
  2. applies `--key=value` CLI overrides, literal-eval'ing values so
     `--lr=3e-4` stays a float and `--compile=False` a bool.

Overriding a key that has no default is an error (fail loud, like the
partition-rule miss policy in SURVEY.md §4). Shared by both backends so the
same argv drives CUDA and TPU runs (BASELINE.json:5).
"""

import sys
from ast import literal_eval


def configure(g, argv=None, allow_new_keys=False):
    """Apply config-file + --key=value overrides to the dict `g` (usually the
    caller's globals()). Returns the list of (key, value) overrides applied."""
    argv = list(sys.argv[1:] if argv is None else argv)
    applied = []
    for arg in argv:
        if "=" not in arg:
            # assume it's a config file path
            assert not arg.startswith("--"), f"flag {arg!r} must look like --key=value"
            config_file = arg
            print(f"[configurator] overriding config with {config_file}:")
            with open(config_file) as f:
                code = f.read()
            print(code)
            known = set(g)
            exec(code, g)
            new_keys = [
                k for k in set(g) - known
                if not k.startswith("_") and isinstance(g[k], (int, float, bool, str))
            ]
            if new_keys and not allow_new_keys:
                raise ValueError(
                    f"config file {config_file} sets unknown key(s): {sorted(new_keys)}"
                )
            applied.append(("__config_file__", config_file))
        else:
            assert arg.startswith("--"), f"override {arg!r} must look like --key=value"
            key, val = arg[2:].split("=", 1)
            if key not in g and not allow_new_keys:
                raise ValueError(f"unknown config key: {key}")
            try:
                attempt = literal_eval(val)
            except (SyntaxError, ValueError):
                attempt = val  # it's a bare string
            default = g.get(key)
            if default is not None and attempt is not None:
                assert isinstance(attempt, type(default)) or (
                    isinstance(attempt, (int, float)) and isinstance(default, (int, float))
                ), f"--{key}: {type(attempt).__name__} does not match default {type(default).__name__}"
            print(f"[configurator] overriding: {key} = {attempt}")
            g[key] = attempt
            applied.append((key, attempt))
    return applied
